"""Preemptible queries: checkpointed park/resume, cancellation, and the
serving scheduler's priority preemption
(``tensorframes_tpu/engine/preempt.py``, ``memory/checkpoint.py``,
``serve/scheduler.py``).

The acceptance spine: a query preempted at a block boundary (driven
deterministically by ``TFT_FAULTS=preempt:N``, the same way ``device:1``
drives elastic recovery) parks its completed block outputs as a
checkpoint, resumes re-dispatching ONLY the remaining blocks (the
pipeline counters prove it), and collects a result bit-identical to an
uninterrupted run. Cancellation settles queued and running queries to
exactly one terminal state with slot accounting balanced.
"""

import threading
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import memory as tmem
from tensorframes_tpu import resilience as rz
from tensorframes_tpu.engine import preempt as pp
from tensorframes_tpu.memory.checkpoint import QueryCheckpoint
from tensorframes_tpu.observability import events as obs_events
from tensorframes_tpu.resilience import (QueryCancelled, QueryPreempted,
                                         faults)
from tensorframes_tpu.serve.scheduler import QueryScheduler, TenantQuota
from tensorframes_tpu.utils import tracing
from tensorframes_tpu.utils.tracing import counters

from conftest import timing_margin

pytestmark = pytest.mark.preempt


@pytest.fixture(autouse=True)
def _clean():
    counters.reset()
    faults.reset()
    yield
    faults.reset()
    tracing.disable()


def _chain(n=40, parts=8, mul=2.0):
    return tft.frame({"x": np.arange(float(n))},
                     num_partitions=parts).map_rows(
        lambda x: {"y": x * mul})


def _ys(frame):
    return [r["y"] for r in frame.collect()]


# ---------------------------------------------------------------------------
# classification + fault site
# ---------------------------------------------------------------------------

class TestClassification:
    def test_kinds_and_transience(self):
        p = QueryPreempted("parked")
        c = QueryCancelled("stopped")
        assert rz.error_kind(p) == "preempted"
        assert rz.error_kind(c) == "cancelled"
        assert not rz.is_transient(p)
        assert not rz.is_transient(c)
        # "CANCELLED" is a transient PJRT status word; the CLASS must
        # win over the marker scan even if the message contains it
        assert not rz.is_transient(QueryCancelled("CANCELLED by user"))

    def test_tft_faults_env_arms_preempt_site(self, monkeypatch):
        monkeypatch.setenv("TFT_FAULTS", "preempt:2")
        monkeypatch.setattr(faults._state, "_armed_env", False)
        assert faults.active("preempt") == 2

    def test_interrupted_never_retried_by_policy(self):
        calls = {"n": 0}

        def work():
            calls["n"] += 1
            raise QueryPreempted("park me")

        with pytest.raises(QueryPreempted):
            rz.default_policy().call(work, op="test")
        assert calls["n"] == 1  # no retry of a scheduler decision


# ---------------------------------------------------------------------------
# engine: park at a boundary, resume only the remaining blocks
# ---------------------------------------------------------------------------

class TestEngineParkResume:
    def test_windowed_park_resume_bit_identical(self):
        df = _chain(40, 8)
        sc = pp.PreemptionScope("q")
        faults.arm("preempt", 1)
        with pytest.raises(QueryPreempted):
            with pp.activate(sc):
                df.blocks()
        parked = counters.get("pipeline.parked_blocks")
        assert parked >= 1
        assert sc.checkpoint is not None and not sc.checkpoint.empty
        sub0 = counters.get("pipeline.submitted")
        with pp.activate(sc):
            out = df.blocks()
        # resume re-dispatched ONLY the remaining blocks
        assert counters.get("pipeline.resumed_blocks") == parked
        assert counters.get("pipeline.submitted") - sub0 == 8 - parked
        assert _ys(df) == _ys(_chain(40, 8))
        assert len(out) == 8

    def test_serial_depth1_park_resume(self, monkeypatch):
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "1")
        df = _chain(40, 8, mul=3.0)
        sc = pp.PreemptionScope("q")
        faults.arm("preempt", 1)
        with pytest.raises(QueryPreempted):
            with pp.activate(sc):
                df.blocks()
        parked = counters.get("pipeline.parked_blocks")
        assert parked >= 1
        with pp.activate(sc):
            df.blocks()
        assert counters.get("pipeline.resumed_blocks") == parked
        assert _ys(df) == _ys(_chain(40, 8, mul=3.0))

    def test_per_op_path_park_resume(self, monkeypatch):
        monkeypatch.setenv("TFT_FUSE", "0")
        df = _chain(40, 8, mul=5.0)
        sc = pp.PreemptionScope("q")
        faults.arm("preempt", 1)
        with pytest.raises(QueryPreempted):
            with pp.activate(sc):
                df.blocks()
        with pp.activate(sc):
            df.blocks()
        monkeypatch.delenv("TFT_FUSE")
        assert _ys(df) == _ys(_chain(40, 8, mul=5.0))

    def test_repeated_preemption_converges(self):
        # budget > needed: every injected preemption must park at a
        # strictly later cursor, so N preemptions never livelock
        df = _chain(40, 8, mul=7.0)
        sc = pp.PreemptionScope("q")
        faults.arm("preempt", 3)
        parks = 0
        for _ in range(10):
            try:
                with pp.activate(sc):
                    df.blocks()
                break
            except QueryPreempted:
                parks += 1
        else:
            pytest.fail("preemption did not converge")
        assert parks == 3
        assert _ys(df) == _ys(_chain(40, 8, mul=7.0))

    def test_cancel_raises_at_boundary_and_frees_checkpoint(self):
        df = _chain(40, 8)
        sc = pp.PreemptionScope("q")
        faults.arm("preempt", 1)
        with pytest.raises(QueryPreempted):
            with pp.activate(sc):
                df.blocks()
        assert not sc.checkpoint.empty
        sc.request_cancel("user")
        with pytest.raises(QueryCancelled):
            with pp.activate(sc):
                df.blocks()
        assert sc.checkpoint.empty  # a cancelled query never resumes

    def test_preempt_event_and_summary(self):
        df = _chain(40, 8)
        sc = pp.PreemptionScope("q")
        faults.arm("preempt", 1)
        tracing.enable()
        try:
            with pytest.raises(QueryPreempted):
                with pp.activate(sc):
                    df.blocks()
            t = obs_events.last_query()
            assert t.count("preempt_park") == 1
            assert t.summary()["preempts"] == 1
            with pp.activate(sc):
                df.blocks()
            t2 = obs_events.last_query()
            assert t2.summary()["resumed_blocks"] >= 1
        finally:
            tracing.disable()


# ---------------------------------------------------------------------------
# the checkpoint itself
# ---------------------------------------------------------------------------

class TestQueryCheckpoint:
    def test_block_and_dict_round_trip(self):
        from tensorframes_tpu.frame import Block
        b = Block({"x": np.arange(5.0),
                   "s": np.array(["a", "b", "c", "d", "e"], object)}, 5)
        d = {"y": np.arange(3, dtype=np.int64)}
        cp = QueryCheckpoint("q")
        cp.park_stream([b, d], total=4)
        out = cp.resume_stream(4)
        assert isinstance(out[0], Block)
        np.testing.assert_array_equal(out[0].columns["x"], b.columns["x"])
        assert list(out[0].columns["s"]) == ["a", "b", "c", "d", "e"]
        np.testing.assert_array_equal(out[1]["y"], d["y"])
        assert cp.empty

    def test_device_arrays_spill_and_fault_back_bitwise(self):
        import jax
        tmem.configure(limit_bytes=1 << 30)
        try:
            a = jax.device_put(np.arange(1000, dtype=np.float32))
            cp = QueryCheckpoint("q")
            moved = cp.park_stream([a], total=1)
            assert moved == 4000
            assert counters.get("memory.spills") == 1
            out = cp.resume_stream(1)
            np.testing.assert_array_equal(
                np.asarray(out[0]), np.arange(1000, dtype=np.float32))
            assert counters.get("memory.faults") == 1
        finally:
            tmem._reset()

    def test_mismatched_stream_discards(self):
        cp = QueryCheckpoint("q")
        cp.park_stream([{"x": np.arange(2)}], total=4)
        assert cp.resume_stream(6) is None  # plan changed: discard
        assert counters.get("serve.checkpoint_discards") == 1
        assert cp.empty

    def test_mismatched_tag_discards(self):
        # same block count but a DIFFERENT execution path (a fused
        # plan that fell back per-op between park and resume) must
        # discard, never restore the wrong stream's outputs
        cp = QueryCheckpoint("q")
        cp.park_stream([{"x": np.arange(2)}], total=4, tag="plan[2ops]")
        assert cp.resume_stream(4, tag="map_rows(source)") is None
        assert counters.get("serve.checkpoint_discards") == 1
        assert cp.empty

    def test_free_drops_parked_state(self):
        cp = QueryCheckpoint("q")
        cp.park_stream([{"x": np.arange(2)}], total=4)
        cp.free()
        assert cp.empty and cp.resume_stream(4) is None


# ---------------------------------------------------------------------------
# scheduler: preempt/resume, cancel, races
# ---------------------------------------------------------------------------

class TestSchedulerPreemption:
    def test_fault_driven_preempt_requeues_and_resumes(self):
        with QueryScheduler(workers=0, name="tp") as s:
            df = _chain(40, 8)
            q = s.submit(df, tenant="whale")
            faults.arm("preempt", 1)
            assert s.step() is True
            assert q.state == "queued" and q.preemptions == 1
            assert q._checkpoint is not None
            assert not q.done()  # preemption is not a terminal state
            sub0 = counters.get("pipeline.submitted")
            parked = counters.get("pipeline.parked_blocks")
            assert s.step() is True
            res = q.result(timeout=10)
            assert q.state == "done"
            assert counters.get("serve.preemptions") == 1
            assert counters.get("pipeline.resumed_blocks") == parked
            assert counters.get("pipeline.submitted") - sub0 == 8 - parked
            assert _ys(res) == _ys(_chain(40, 8))
            assert s.snapshot()["whale"]["preempted"] == 1
            assert s.snapshot()["whale"]["completed"] == 1

    def test_cancel_queued_never_runs(self):
        with QueryScheduler(workers=0, name="tc") as s:
            q = s.submit(_chain(), tenant="t")
            assert s.cancel(q.query_id) is True
            with pytest.raises(QueryCancelled):
                q.result(timeout=2)
            assert q.state == "cancelled"
            assert s.step() is False  # nothing left to run
            assert s.cancel(q.query_id) is False  # double-cancel: no-op
            snap = s.snapshot()["t"]
            assert snap["cancelled"] == 1 and snap["completed"] == 0
            assert snap["queued"] == 0 and snap["inflight"] == 0

    def test_cancel_running_settles_once(self):
        with QueryScheduler(workers=0, name="tr") as s:
            df = tft.frame({"x": np.arange(2000.0)},
                           num_partitions=32).map_rows(
                lambda x: {"y": x * 2}).map_rows(lambda y: {"z": y + 1})
            q = s.submit(df, tenant="t")
            th = threading.Thread(target=s.step)
            th.start()
            for _ in range(2000):
                if q.state != "queued":
                    break
                time.sleep(0.005)
            assert s.cancel(q.query_id) is True
            th.join(timeout=30)
            assert not th.is_alive()
            with pytest.raises(QueryCancelled):
                q.result(timeout=10)
            # exactly one terminal state, accounting balanced
            assert q.state == "cancelled"
            assert q._checkpoint is None
            snap = s.snapshot()["t"]
            assert snap["inflight"] == 0 and snap["queued"] == 0
            assert s.query(q.query_id) is None
            # every pipeline slot is back in the pool
            for _ in range(s.slot_pool.slots):
                assert s.slot_pool.try_acquire()
            for _ in range(s.slot_pool.slots):
                s.slot_pool.release()

    def test_double_cancel_running_is_idempotent(self):
        with QueryScheduler(workers=0, name="td") as s:
            df = _chain(400, 16)
            q = s.submit(df, tenant="t")
            th = threading.Thread(target=s.step)
            th.start()
            for _ in range(2000):
                if q.state != "queued":
                    break
                time.sleep(0.005)
            first = s.cancel(q.query_id)
            second = s.cancel(q.query_id)
            th.join(timeout=30)
            assert first is True
            # the second call either raced the terminal transition
            # (False) or re-flagged a still-running query (True) — but
            # the query settles exactly once either way
            assert second in (True, False)
            assert q.state == "cancelled"
            assert s.snapshot()["t"]["cancelled"] == 1

    def test_preempt_racing_natural_completion(self):
        # a preempt request that lands with only already-dispatched
        # work left parks an almost-complete prefix; the resumed run
        # restores it and finishes — never two terminal states, never
        # a lost result. Driven 3x with requests at random points.
        rng = np.random.default_rng(7)
        for trial in range(3):
            with QueryScheduler(workers=0, name=f"race{trial}") as s:
                df = _chain(200, 16, mul=float(trial + 2))
                q = s.submit(df, tenant="t")
                done = threading.Event()

                def drive():
                    while s.step():
                        pass
                    done.set()

                th = threading.Thread(target=drive)
                th.start()
                # fire a preempt request at a random moment mid-run
                time.sleep(float(rng.uniform(0.0, 0.05)))
                live = s.query(q.query_id)
                if live is not None and live._scope is not None:
                    live._scope.request_preempt("race test")
                # the drive loop exits when the queue empties; a parked
                # query re-queues, so keep stepping until terminal
                th.join(timeout=30)
                while not q.done() and s.step():
                    pass
                res = q.result(timeout=10)
                assert q.state == "done"
                assert _ys(res) == _ys(_chain(200, 16,
                                              mul=float(trial + 2)))
                snap = s.snapshot()["t"]
                assert snap["inflight"] == 0 and snap["queued"] == 0

    def test_priority_arrival_preempts_lowest_weight_whale(
            self, monkeypatch):
        monkeypatch.setenv("TFT_PREEMPT_AFTER_MS", "0")
        with QueryScheduler(
                quotas={"whale": TenantQuota(weight=1.0),
                        "vip": TenantQuota(weight=8.0)},
                workers=0, name="pp") as s:
            whale_df = tft.frame({"x": np.arange(20_000.0)},
                                 num_partitions=24).map_rows(
                lambda x: {"y": x * 2}).map_rows(lambda y: {"z": y + 1})
            wq = s.submit(whale_df, tenant="whale")
            stepped = threading.Event()

            def run_whale():
                s.step()
                stepped.set()

            th = threading.Thread(target=run_whale)
            th.start()
            for _ in range(2000):
                if wq.state == "running":
                    break
                time.sleep(0.002)
            assert wq.state == "running", "whale never started"
            vq = s.submit(tft.frame({"x": np.arange(8.0)}).map_rows(
                lambda x: {"y": x + 1}), tenant="vip")
            assert stepped.wait(30), "whale neither parked nor finished"
            th.join(timeout=5)
            assert wq.preemptions >= 1, \
                "arrival of a higher-weight tenant did not preempt"
            assert counters.get("serve.preempt_requests") >= 1
            # the fair pick serves the vip FIRST, then resumes the whale
            assert s.step() is True
            assert vq.result(timeout=10) is not None
            while not wq.done():
                assert s.step() is True
            assert _ys(wq.result(timeout=10)) == _ys(whale_df)
            snap = s.snapshot()
            assert snap["whale"]["preempted"] >= 1
            assert snap["whale"]["completed"] == 1
            assert snap["vip"]["completed"] == 1

    @pytest.mark.timing
    def test_cancel_aborts_admission_wait(self, monkeypatch):
        # review regression: a cancel landing while the query waits for
        # HBM admission (no scope exists yet) must not be lost — the
        # wait aborts and the query settles cancelled, not "done"
        monkeypatch.setenv("TFT_SERVE_ADMISSION_WAIT_S", "30")
        with QueryScheduler(workers=0, name="aw") as s:
            monkeypatch.setattr(s, "_hbm_headroom", lambda: 0)
            q = s.submit(_chain(), tenant="t", est_bytes=10_000)
            th = threading.Thread(target=s.step)
            th.start()
            for _ in range(2000):
                if q.state == "running":
                    break
                time.sleep(0.002)
            assert s.cancel(q.query_id) is True
            th.join(timeout=timing_margin(10.0))
            assert not th.is_alive(), "admission wait ignored the cancel"
            with pytest.raises(QueryCancelled):
                q.result(timeout=5)
            assert q.state == "cancelled"

    def test_anonymous_stream_preempts_without_checkpoint(self):
        # an ad-hoc PipelinedExecutor.map stream has no stable identity
        # to resume into: it must yield WITHOUT parking (two anonymous
        # streams of equal length must never restore each other)
        from tensorframes_tpu.engine.executor import default_executor
        from tensorframes_tpu.engine.pipeline import PipelinedExecutor
        from tensorframes_tpu.engine.ops import _map_computation
        from tensorframes_tpu.schema import Schema
        df = tft.frame({"x": np.arange(24.0)}, num_partitions=6)
        schema = Schema.of(x="double")
        comp = _map_computation(lambda x: {"y": x * 2}, schema,
                                block_level=True)
        arrays = [{"x": b.columns["x"]} for b in df.blocks()]
        pex = PipelinedExecutor(default_executor(), depth=3)
        sc = pp.PreemptionScope("anon")
        faults.arm("preempt", 1)
        with pytest.raises(QueryPreempted):
            with pp.activate(sc):
                pex.map(arrays, comp)
        assert sc.checkpoint is None or sc.checkpoint.empty
        with pp.activate(sc):
            out = pex.map(arrays, comp)  # full re-run, nothing restored
        assert counters.get("pipeline.resumed_blocks") == 0
        np.testing.assert_array_equal(
            np.concatenate([o["y"] for o in out]),
            np.arange(24.0) * 2)

    def test_preemption_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("TFT_SERVE_PREEMPT", "0")
        with QueryScheduler(workers=0, name="off") as s:
            assert s._preemption is False

    def test_metrics_families_exported(self):
        from tensorframes_tpu.observability.metrics import metrics_text
        with QueryScheduler(workers=0, name="tm") as s:
            q = s.submit(_chain(), tenant="t")
            faults.arm("preempt", 1)
            s.step()
            s.step()
            q.result(timeout=10)
            text = metrics_text()
        assert "tft_serve_preemptions_total 1" in text
        assert "tft_serve_resumed_blocks_total" in text
        assert 'outcome="preempted"}' in text

    @pytest.mark.timing
    def test_cancel_latency_bounded(self):
        # the preempt-latency bound: a cancel lands at the next block
        # boundary, not at the end of the whale
        with QueryScheduler(workers=0, name="tl") as s:
            df = tft.frame({"x": np.arange(50_000.0)},
                           num_partitions=64).map_rows(
                lambda x: {"y": x * 2}).map_rows(lambda y: {"z": y * 3})
            q = s.submit(df, tenant="t")
            th = threading.Thread(target=s.step)
            th.start()
            for _ in range(4000):
                if q.state != "queued":
                    break
                time.sleep(0.002)
            t0 = time.monotonic()
            s.cancel(q.query_id)
            assert q._event.wait(timing_margin(15.0)), \
                "cancel did not settle within its margin"
            assert time.monotonic() - t0 <= timing_margin(15.0)
            th.join(timeout=10)
            assert q.state == "cancelled"

    def test_close_fails_parked_query_and_frees_checkpoint(self):
        s = QueryScheduler(workers=0, name="tz")
        try:
            q = s.submit(_chain(40, 8), tenant="t")
            faults.arm("preempt", 1)
            s.step()
            assert q.preemptions == 1 and not q.done()
            cp = q._checkpoint
            assert cp is not None and not cp.empty
        finally:
            s.close()
        with pytest.raises(rz.ServeRejected):
            q.result(timeout=2)
        assert q.state == "rejected"
        assert q._checkpoint is None and cp.empty  # freed on terminal


# ---------------------------------------------------------------------------
# streams: interruption is control flow, not poisoned data
# ---------------------------------------------------------------------------

class TestStreamInterruption:
    def test_cancel_propagates_not_skip_counted(self):
        from tensorframes_tpu import stream as tstream
        src = tstream.GeneratorSource(
            ({"x": np.arange(4.0) + i} for i in range(100)))
        handle = tstream.StreamingFrame(src).map_rows(
            lambda x: {"y": x * 2}).start()
        assert handle.step(timeout=1.0) is True  # healthy batch first
        sc = pp.PreemptionScope("op")
        sc.request_cancel("operator stop")
        with pytest.raises(QueryCancelled):
            with pp.activate(sc):
                handle.step(timeout=1.0)
        m = handle.metrics()
        assert m["batches_skipped"] == 0  # not counted as poisoned
        assert m["batches"] == 1
        handle.stop()
