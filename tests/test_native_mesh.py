"""Mesh ops through the native C++ PJRT core (GSPMD), parity vs jax.

The reference's property that every execution bottoms out in C++
(``TensorFlowOps.scala:55-64``) extended to the DISTRIBUTED layer: the
same mesh programs dmap_blocks/dreduce_blocks build, GSPMD-compiled and
executed by ``native/libtfrpjrt.so`` on a cpu:4 client, must match the
in-process jax dispatch bit-for-bit (same XLA, same partitioner).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import tensorframes_tpu as tft
from tensorframes_tpu import parallel as par
from tensorframes_tpu.parallel import native_mesh


def _native_available() -> bool:
    from tensorframes_tpu import native_pjrt

    return native_pjrt.available()


pytestmark = pytest.mark.skipif(
    not _native_available(),
    reason="libtfrpjrt.so not built (make -C native pjrt)")


@pytest.fixture
def mesh4():
    return par.local_mesh(4)


@pytest.fixture
def pjrt_routing(monkeypatch):
    monkeypatch.setenv("TFT_EXECUTOR", "pjrt")


def _executor(mesh4):
    ex = native_mesh.executor_for(mesh4)
    assert ex is not None, "native mesh executor should be available"
    return ex


class TestNativeDmap:
    def test_parity_with_jax_path(self, mesh4, pjrt_routing):
        x = np.arange(32, dtype=np.float64)
        df = tft.frame({"x": x})
        fetch = lambda x: {"z": x * 2.0 + 1.0}  # noqa: E731

        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.dmap_blocks(fetch, dist)
        assert ex.dispatch_count == before + 1  # the native core ran it
        got = np.asarray(out.columns["z"])

        # identical program through the in-process jax dispatch
        import os

        os.environ.pop("TFT_EXECUTOR", None)
        ref = par.dmap_blocks(fetch, par.distribute(df, mesh4))
        np.testing.assert_array_equal(got, np.asarray(ref.columns["z"]))

    def test_vector_columns_and_collect(self, mesh4, pjrt_routing):
        v = np.arange(24, dtype=np.float64).reshape(12, 2)
        df = tft.analyze(tft.frame({"v": v}))
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.dmap_blocks(lambda v: {"s": v.sum(axis=1)}, dist)
        assert ex.dispatch_count == before + 1
        rows = out.collect_frame().collect()
        np.testing.assert_allclose([r["s"] for r in rows], v.sum(axis=1))

    def test_pad_rows_flow_through(self, mesh4, pjrt_routing):
        # 10 rows over 4 shards pads to 12; pad rows must be dropped at
        # collect exactly as on the jax path
        x = np.arange(10, dtype=np.float64)
        dist = par.distribute(tft.frame({"x": x}), mesh4)
        out = par.dmap_blocks(lambda x: {"z": x + 3.0}, dist)
        rows = out.collect_frame().collect()
        assert [r["z"] for r in rows] == [v + 3.0 for v in x]

    def test_trim_falls_back_to_jax(self, mesh4, pjrt_routing):
        # a global (row-count-changing) computation cannot take the
        # native route; it must still produce the right answer via jax —
        # including the ONE-summary-row case, whose row count does not
        # even tile the data axis
        x = np.arange(8, dtype=np.float64)
        dist = par.distribute(tft.frame({"x": x}), mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.dmap_blocks(
            lambda x: {"s": x.sum(keepdims=True)}, dist, trim=True,
            row_aligned=False)
        assert ex.dispatch_count == before  # native path not used
        rows = out.collect_frame().collect()
        assert len(rows) == 1
        np.testing.assert_allclose(rows[0]["s"], x.sum())

    def test_compile_cache_reused(self, mesh4, pjrt_routing):
        # one live Computation, two dispatches -> one native compile
        # (the cache lives on the Computation, the _tft_jitted pattern)
        from tensorframes_tpu import dtypes as _dt
        from tensorframes_tpu.computation import Computation, TensorSpec
        from tensorframes_tpu.shape import Shape, Unknown

        comp = Computation.trace(
            lambda x: {"z": x - 1.0},
            [TensorSpec("x", _dt.double, Shape(Unknown))])
        x = np.arange(16, dtype=np.float64)
        dist = par.distribute(tft.frame({"x": x}), mesh4)
        ex = _executor(mesh4)
        before = ex.compile_count
        par.dmap_blocks(comp, dist)
        par.dmap_blocks(comp, dist)
        assert ex.compile_count == before + 1  # second call hit the cache


class TestNativeDreduce:
    def test_sum_min_parity(self, mesh4, pjrt_routing):
        rng = np.random.default_rng(7)
        x = rng.normal(size=100)
        df = tft.frame({"x": x})
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.dreduce_blocks({"x": "sum"}, dist)
        assert ex.dispatch_count == before + 1
        np.testing.assert_allclose(out["x"], x.sum(), rtol=1e-12)

        out2 = par.dreduce_blocks({"x": "min"}, dist)
        np.testing.assert_allclose(out2["x"], x.min())

    def test_vector_column_and_pad_masking(self, mesh4, pjrt_routing):
        # 10 rows pad to 12: the two pad rows must be masked to the
        # neutral element inside the native program too
        v = np.arange(20, dtype=np.float64).reshape(10, 2)
        df = tft.analyze(tft.frame({"v": v}))
        dist = par.distribute(df, mesh4)
        out = par.dreduce_blocks({"v": "sum"}, dist)
        np.testing.assert_allclose(out["v"], v.sum(axis=0))

    def test_generic_computation_runs_natively(self, mesh4, pjrt_routing):
        # the arbitrary-computation reduce (per-shard partials + ragged
        # tail + final stacked combine) compiles as one GSPMD executable
        import os

        rng = np.random.default_rng(13)
        x = rng.normal(size=42)  # 42 over 4 shards: tail shard exercised
        df = tft.frame({"x": x})
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count

        def fetch(x_input):
            return {"x": jnp.sqrt((x_input ** 2).sum(0))}

        out = par.dreduce_blocks(fetch, dist)
        assert ex.dispatch_count == before + 1
        os.environ.pop("TFT_EXECUTOR", None)
        ref = par.dreduce_blocks(fetch, par.distribute(df, mesh4))
        np.testing.assert_array_equal(out["x"], ref["x"])

    def test_matches_jax_path_exactly(self, mesh4, pjrt_routing):
        # same partitioner, same program -> same floats up to reduction
        # order. The native core may be built against a different XLA
        # (tensorflow's) than jaxlib's, so bit-exactness across the two
        # builds is not guaranteed — hold them to ~1 ULP instead.
        import os

        rng = np.random.default_rng(11)
        x = rng.normal(size=64)
        dist = par.distribute(tft.frame({"x": x}), mesh4)
        native = par.dreduce_blocks({"x": "sum"}, dist)
        os.environ.pop("TFT_EXECUTOR", None)
        ref = par.dreduce_blocks({"x": "sum"},
                                 par.distribute(tft.frame({"x": x}), mesh4))
        np.testing.assert_allclose(native["x"], ref["x"],
                                   rtol=1e-15, atol=0)


class TestNativeDsortDfilter:
    def test_dsort_parity_with_jax_path(self, mesh4, pjrt_routing):
        import os

        rng = np.random.default_rng(21)
        x = rng.normal(size=600)
        x[::71] = np.nan
        dist = par.distribute(tft.frame({"x": x}), mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.dsort("x", dist, descending=True)
        assert ex.dispatch_count == before + 1  # columnsort ran natively
        got = np.asarray(out.columns["x"])
        os.environ.pop("TFT_EXECUTOR", None)
        ref = par.dsort("x", par.distribute(tft.frame({"x": x}), mesh4),
                        descending=True)
        np.testing.assert_array_equal(got, np.asarray(ref.columns["x"]))

    def test_dsort_collect_with_string_riders(self, mesh4, pjrt_routing):
        k = np.array([f"s{i}" for i in range(10)], object)
        x = np.arange(10, dtype=np.float64)[::-1].copy()
        dist = par.distribute(tft.frame({"k": k, "x": x}), mesh4)
        rows = par.dsort("x", dist).collect_frame().collect()
        assert [r["k"] for r in rows] == [f"s{i}" for i in range(9, -1, -1)]

    def test_dfilter_parity_and_chain(self, mesh4, pjrt_routing):
        x = np.arange(40, dtype=np.float64)
        dist = par.distribute(tft.frame({"x": x}), mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        flt = par.dfilter(lambda x: x % 3.0 == 0.0, dist)
        assert ex.dispatch_count == before + 1
        assert flt.count() == 14
        # chain into a native reduce and a native sort
        red = par.dreduce_blocks({"x": "sum"}, flt.select("x"))
        np.testing.assert_allclose(red["x"], x[x % 3 == 0].sum())
        srt = par.dsort("x", flt, descending=True)
        rows = srt.collect_frame().collect()
        assert [r["x"] for r in rows] == sorted(
            x[x % 3 == 0].tolist(), reverse=True)


class TestNativeDaggregate:
    """daggregate through the C++ core — the last mesh op to gain the
    route (reference property: every UDAF compaction ran in the C++
    session, ``DebugRowOps.scala:617-662``)."""

    def test_monoid_parity_with_jax_path(self, mesh4, pjrt_routing):
        import os

        rng = np.random.default_rng(31)
        n, g = 200, 17
        keys = rng.integers(0, g, n).astype(np.int64)
        vals = rng.normal(size=n)
        df = tft.frame({"key": keys, "x": vals})
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.daggregate({"x": "sum"}, dist, "key")
        assert ex.dispatch_count == before + 1  # native core ran it
        got = {r["key"]: r["x"] for r in out.collect()}

        os.environ.pop("TFT_EXECUTOR", None)
        ref_out = par.daggregate({"x": "sum"},
                                 par.distribute(df, mesh4), "key")
        ref = {r["key"]: r["x"] for r in ref_out.collect()}
        assert set(got) == set(ref)
        for k in ref:  # same XLA, same partitioner -> identical floats
            np.testing.assert_array_equal(got[k], ref[k])

    def test_monoid_min_vector_column(self, mesh4, pjrt_routing):
        rng = np.random.default_rng(32)
        k = rng.integers(0, 5, 30).astype(np.int64)
        v = rng.normal(size=(30, 2))
        df = tft.analyze(tft.frame({"k": k, "v": v}))
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.daggregate({"v": "min"}, dist, "k")
        assert ex.dispatch_count == before + 1
        for r in out.collect():
            np.testing.assert_allclose(
                r["v"], v[k == r["k"]].min(axis=0), rtol=1e-12)

    def test_device_key_composite_parity(self, mesh4, pjrt_routing):
        # composite (mixed-radix) device-side keys: the key columns never
        # visit the host; the aggregation program still runs natively
        import os

        rng = np.random.default_rng(33)
        k1 = rng.integers(0, 4, 60).astype(np.int64)
        k2 = rng.integers(0, 3, 60).astype(np.int64)
        x = rng.normal(size=60)
        df = tft.frame({"k1": k1, "k2": k2, "x": x})
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.daggregate({"x": "sum"}, dist, ["k1", "k2"],
                             max_groups=16)
        assert ex.dispatch_count > before
        got = {(r["k1"], r["k2"]): r["x"] for r in out.collect()}

        os.environ.pop("TFT_EXECUTOR", None)
        ref_out = par.daggregate({"x": "sum"}, par.distribute(df, mesh4),
                                 ["k1", "k2"])
        ref = {(r["k1"], r["k2"]): r["x"] for r in ref_out.collect()}
        assert set(got) == set(ref)
        for k in ref:
            np.testing.assert_allclose(got[k], ref[k], rtol=1e-12)

    def test_integer_sum_exact(self, mesh4, pjrt_routing):
        # int64 sums must stay exact through the native route (the XLA
        # scatter-add flavor is forced exactly because the Pallas one-hot
        # matmul accumulates in f32)
        rng = np.random.default_rng(35)
        k = rng.integers(0, 6, 64).astype(np.int64)
        # values near 2^53: per-key sums leave f64's exact-integer range,
        # so a silent float detour (f32 OR f64 accumulation) fails loudly
        x = rng.integers(2**53 - 2**20, 2**53, 64).astype(np.int64)
        df = tft.frame({"k": k, "x": x})
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.daggregate({"x": "sum"}, dist, "k")
        assert ex.dispatch_count == before + 1
        got = {r["k"]: r["x"] for r in out.collect()}
        for kk in np.unique(k):
            assert got[kk] == x[k == kk].sum(), kk  # exact, not approx

    def test_generic_fold_runs_natively(self, mesh4, pjrt_routing):
        # the arbitrary-computation (sorted-scan) path compiles as one
        # GSPMD executable too
        import os

        import jax.numpy as jnp

        rng = np.random.default_rng(34)
        n = 120
        k = rng.integers(0, 7, n).astype(np.int64)
        v = rng.normal(size=n)

        def fetch(v_input):
            return {"v": jnp.sqrt((v_input ** 2).sum(0))}

        df = tft.frame({"k": k, "v": v})
        dist = par.distribute(df, mesh4)
        ex = _executor(mesh4)
        before = ex.dispatch_count
        out = par.daggregate(fetch, dist, "k")
        assert ex.dispatch_count == before + 1
        got = {r["k"]: r["v"] for r in out.collect()}

        os.environ.pop("TFT_EXECUTOR", None)
        ref_out = par.daggregate(fetch, par.distribute(df, mesh4), "k")
        ref = {r["k"]: r["v"] for r in ref_out.collect()}
        assert set(got) == set(ref)
        for key in ref:
            np.testing.assert_array_equal(got[key], ref[key])


class TestResidentLoop:
    """Device-resident iteration through the native core: shards upload
    once, outputs feed back as device buffers, one final download —
    the HBM-resident loop the jax path gets from ``jax.Array``."""

    def test_loop_matches_per_call_dispatch(self, mesh4, pjrt_routing):
        import jax.numpy as jnp
        from tensorframes_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        ex = _executor(mesh4)
        axis = mesh4.data_axis
        n = 16
        x = np.arange(n, dtype=np.float64)

        def build():
            def step(x):
                # a collective every iteration proves the ICI path runs
                # inside the resident loop too
                total = jax.lax.psum(x.sum(), axis)
                return (x * 0.5 + total / n,)
            return shard_map(step, mesh=mesh4.mesh,
                             in_specs=(P(axis),), out_specs=(P(axis),))

        in_sh = [mesh4.row_sharding(1)]
        out_sh = [mesh4.row_sharding(1)]
        iters = 5
        before = ex.dispatch_count
        looped = ex.run_sharded_loop(("loop-test", n), build, [x], in_sh,
                                     out_sh, mesh4, iters=iters)
        assert looped is not None
        assert ex.dispatch_count == before + iters

        # reference: the same program applied per-call via jax
        fn = jax.jit(build())
        ref = jnp.asarray(x)
        for _ in range(iters):
            (ref,) = fn(ref)
        np.testing.assert_allclose(looped[0], np.asarray(ref), rtol=1e-12)

    def test_loop_multi_arg_mixed_dtypes(self, mesh4, pjrt_routing):
        # two-state loop (f64 vector + i32 counter), both resident
        from tensorframes_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        ex = _executor(mesh4)
        axis = mesh4.data_axis
        x = np.arange(8, dtype=np.float64)
        c = np.zeros(8, dtype=np.int32)

        def build():
            def step(x, c):
                return (x * 2.0, c + 1)
            return shard_map(step, mesh=mesh4.mesh,
                             in_specs=(P(axis), P(axis)),
                             out_specs=(P(axis), P(axis)))

        sh = [mesh4.row_sharding(1), mesh4.row_sharding(1)]
        outs = ex.run_sharded_loop(("loop-multi", 8), build, [x, c],
                                   sh, sh, mesh4, iters=3)
        assert outs is not None, "two-state program should be routable"
        np.testing.assert_array_equal(outs[0], x * 8.0)
        np.testing.assert_array_equal(outs[1], np.full(8, 3, np.int32))

    def test_loop_rejects_signature_mismatch(self, mesh4, pjrt_routing):
        from tensorframes_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        ex = _executor(mesh4)
        axis = mesh4.data_axis
        x = np.arange(8, dtype=np.float64)

        def build():
            return shard_map(lambda x: (x[: x.shape[0] // 2],),
                             mesh=mesh4.mesh, in_specs=(P(axis),),
                             out_specs=(P(axis),))

        with pytest.raises(ValueError, match="positionally"):
            ex.run_sharded_loop(("loop-bad", 8), build, [x],
                                [mesh4.row_sharding(1)],
                                [mesh4.row_sharding(1)], mesh4, iters=2)


class TestRoutingGuards:
    def test_off_without_env(self, mesh4, monkeypatch):
        monkeypatch.delenv("TFT_EXECUTOR", raising=False)
        assert native_mesh.executor_for(mesh4) is None

    def test_string_columns_ride_along(self, mesh4, pjrt_routing):
        # string ride-along columns never enter the computation; the
        # native route must still work for the tensor outputs
        k = np.array([f"k{i}" for i in range(8)], object)
        x = np.arange(8, dtype=np.float64)
        dist = par.distribute(tft.frame({"k": k, "x": x}), mesh4)
        out = par.dmap_blocks(lambda x: {"z": x + 1.0}, dist)
        rows = out.collect_frame().collect()
        assert [(r["k"], r["z"]) for r in rows] == [
            (f"k{i}", float(i) + 1.0) for i in range(8)]
