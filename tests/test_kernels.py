"""Pallas kernel tests, run in interpreter mode on the CPU backend.

The XLA implementations are the semantic oracles (the ExtractNodes pattern
from SURVEY.md §4 applied to kernels: same computation, two lowerings, equal
outputs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.ops import flash_attention, segment_sum


def _qkv(rng, b=2, s=64, h=2, d=16, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla(self, rng, causal):
        q, k, v = _qkv(rng)
        ref = flash_attention(q, k, v, causal=causal, impl="xla")
        out = flash_attention(q, k, v, causal=causal, impl="interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_multiple_seq_len(self, rng):
        # seq length not a multiple of the block: pad rows must not leak
        q, k, v = _qkv(rng, s=37)
        ref = flash_attention(q, k, v, impl="xla")
        out = flash_attention(q, k, v, impl="interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_causal_non_multiple(self, rng):
        q, k, v = _qkv(rng, s=21)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = flash_attention(q, k, v, causal=True, impl="interpret",
                              block_q=8, block_k=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_cross_attention_lengths(self, rng):
        # Sq != Sk (decoder attending over a different-length memory)
        q = jnp.asarray(rng.standard_normal((1, 24, 2, 8)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 40, 2, 8)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 40, 2, 8)), jnp.float32)
        ref = flash_attention(q, k, v, impl="xla")
        out = flash_attention(q, k, v, impl="interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_single_block(self, rng):
        q, k, v = _qkv(rng, s=8)
        ref = flash_attention(q, k, v, impl="xla")
        out = flash_attention(q, k, v, impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_ring_attention(self, rng):
        """Kernel and the mesh-level ring implementation agree — the two
        halves of the long-context story compute the same function."""
        from tensorframes_tpu.parallel.mesh import local_mesh
        from tensorframes_tpu.parallel.ring import ring_attention

        mesh = local_mesh(4)
        q, k, v = _qkv(rng, b=1, s=32, h=2, d=8)
        ref = np.asarray(flash_attention(q, k, v, causal=True, impl="xla"))
        ring = np.asarray(ring_attention(q, k, v, mesh, causal=True))
        flash = np.asarray(flash_attention(q, k, v, causal=True,
                                           impl="interpret",
                                           block_q=8, block_k=8))
        np.testing.assert_allclose(ring, ref, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(flash, ref, rtol=2e-5, atol=2e-5)


class TestSegmentSum:
    def test_matches_xla(self, rng):
        vals = jnp.asarray(rng.standard_normal((100, 5)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 7, 100), jnp.int32)
        ref = segment_sum(vals, ids, 7, impl="xla")
        out = segment_sum(vals, ids, 7, impl="interpret", block_rows=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_out_of_range_ids_dropped(self, rng):
        vals = jnp.ones((10, 2), jnp.float32)
        ids = jnp.asarray([0, 1, -1, 2, 5, 1, 0, -1, 2, 1], jnp.int32)
        out = segment_sum(vals, ids, 3, impl="interpret", block_rows=4)
        ref = segment_sum(vals, ids, 3, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
        # id 5 and -1 dropped: total mass = rows with id in [0, 3)
        assert float(np.asarray(out).sum()) == pytest.approx(2 * 7)

    def test_1d_values(self, rng):
        vals = jnp.asarray(rng.standard_normal(50), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 4, 50), jnp.int32)
        ref = segment_sum(vals, ids, 4, impl="xla")
        out = segment_sum(vals, ids, 4, impl="interpret", block_rows=8)
        assert out.shape == (4,)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_nd_values(self, rng):
        vals = jnp.asarray(rng.standard_normal((30, 2, 3)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 5, 30), jnp.int32)
        ref = segment_sum(vals, ids, 5, impl="xla")
        out = segment_sum(vals, ids, 5, impl="interpret", block_rows=8)
        assert out.shape == (5, 2, 3)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_empty(self):
        vals = jnp.zeros((0, 3), jnp.float32)
        ids = jnp.zeros((0,), jnp.int32)
        out = segment_sum(vals, ids, 4, impl="interpret")
        np.testing.assert_array_equal(np.asarray(out), np.zeros((4, 3)))

    def test_int_values_routed_to_exact_path(self, rng):
        vals = jnp.asarray(rng.integers(-5, 5, (40, 2)), jnp.int32)
        ids = jnp.asarray(rng.integers(0, 3, 40), jnp.int32)
        out = segment_sum(vals, ids, 3)  # default impl: ints -> scatter-add
        ref = np.zeros((3, 2), np.int64)
        np.add.at(ref, np.asarray(ids), np.asarray(vals, np.int64))
        np.testing.assert_array_equal(np.asarray(out, np.int64), ref)
        # an explicit f32-accumulating impl on ints is an error, not silent
        with pytest.raises(ValueError, match="inexact for integer"):
            segment_sum(vals, ids, 3, impl="interpret", block_rows=16)

    def test_unknown_impl_rejected(self, rng):
        vals = jnp.asarray(rng.integers(-5, 5, (4, 2)), jnp.int32)
        ids = jnp.zeros(4, jnp.int32)
        with pytest.raises(ValueError, match="Unknown segment_sum impl"):
            segment_sum(vals, ids, 1, impl="bogus")
