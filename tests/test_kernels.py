"""Pallas kernel tests, run in interpreter mode on the CPU backend.

The XLA implementations are the semantic oracles (the ExtractNodes pattern
from SURVEY.md §4 applied to kernels: same computation, two lowerings, equal
outputs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.ops import flash_attention, segment_sum
from tensorframes_tpu.utils.compat import HAS_VMA


def _qkv(rng, b=2, s=64, h=2, d=16, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla(self, rng, causal):
        q, k, v = _qkv(rng)
        ref = flash_attention(q, k, v, causal=causal, impl="xla")
        out = flash_attention(q, k, v, causal=causal, impl="interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_multiple_seq_len(self, rng):
        # seq length not a multiple of the block: pad rows must not leak
        q, k, v = _qkv(rng, s=37)
        ref = flash_attention(q, k, v, impl="xla")
        out = flash_attention(q, k, v, impl="interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_causal_non_multiple(self, rng):
        q, k, v = _qkv(rng, s=21)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = flash_attention(q, k, v, causal=True, impl="interpret",
                              block_q=8, block_k=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_cross_attention_lengths(self, rng):
        # Sq != Sk (decoder attending over a different-length memory)
        q = jnp.asarray(rng.standard_normal((1, 24, 2, 8)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 40, 2, 8)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 40, 2, 8)), jnp.float32)
        ref = flash_attention(q, k, v, impl="xla")
        out = flash_attention(q, k, v, impl="interpret",
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_single_block(self, rng):
        q, k, v = _qkv(rng, s=8)
        ref = flash_attention(q, k, v, impl="xla")
        out = flash_attention(q, k, v, impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_matches_ring_attention(self, rng):
        """Kernel and the mesh-level ring implementation agree — the two
        halves of the long-context story compute the same function."""
        from tensorframes_tpu.parallel.mesh import local_mesh
        from tensorframes_tpu.parallel.ring import ring_attention

        mesh = local_mesh(4)
        q, k, v = _qkv(rng, b=1, s=32, h=2, d=8)
        ref = np.asarray(flash_attention(q, k, v, causal=True, impl="xla"))
        ring = np.asarray(ring_attention(q, k, v, mesh, causal=True))
        flash = np.asarray(flash_attention(q, k, v, causal=True,
                                           impl="interpret",
                                           block_q=8, block_k=8))
        np.testing.assert_allclose(ring, ref, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(flash, ref, rtol=2e-5, atol=2e-5)


class TestSegmentSum:
    def test_matches_xla(self, rng):
        vals = jnp.asarray(rng.standard_normal((100, 5)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 7, 100), jnp.int32)
        ref = segment_sum(vals, ids, 7, impl="xla")
        out = segment_sum(vals, ids, 7, impl="interpret", block_rows=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_out_of_range_ids_dropped(self, rng):
        vals = jnp.ones((10, 2), jnp.float32)
        ids = jnp.asarray([0, 1, -1, 2, 5, 1, 0, -1, 2, 1], jnp.int32)
        out = segment_sum(vals, ids, 3, impl="interpret", block_rows=4)
        ref = segment_sum(vals, ids, 3, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
        # id 5 and -1 dropped: total mass = rows with id in [0, 3)
        assert float(np.asarray(out).sum()) == pytest.approx(2 * 7)

    def test_1d_values(self, rng):
        vals = jnp.asarray(rng.standard_normal(50), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 4, 50), jnp.int32)
        ref = segment_sum(vals, ids, 4, impl="xla")
        out = segment_sum(vals, ids, 4, impl="interpret", block_rows=8)
        assert out.shape == (4,)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_nd_values(self, rng):
        vals = jnp.asarray(rng.standard_normal((30, 2, 3)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 5, 30), jnp.int32)
        ref = segment_sum(vals, ids, 5, impl="xla")
        out = segment_sum(vals, ids, 5, impl="interpret", block_rows=8)
        assert out.shape == (5, 2, 3)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_empty(self):
        vals = jnp.zeros((0, 3), jnp.float32)
        ids = jnp.zeros((0,), jnp.int32)
        out = segment_sum(vals, ids, 4, impl="interpret")
        np.testing.assert_array_equal(np.asarray(out), np.zeros((4, 3)))

    def test_int_values_routed_to_exact_path(self, rng):
        vals = jnp.asarray(rng.integers(-5, 5, (40, 2)), jnp.int32)
        ids = jnp.asarray(rng.integers(0, 3, 40), jnp.int32)
        out = segment_sum(vals, ids, 3)  # default impl: ints -> scatter-add
        ref = np.zeros((3, 2), np.int64)
        np.add.at(ref, np.asarray(ids), np.asarray(vals, np.int64))
        np.testing.assert_array_equal(np.asarray(out, np.int64), ref)
        # an explicit f32-accumulating impl on ints is an error, not silent
        with pytest.raises(ValueError, match="inexact for integer"):
            segment_sum(vals, ids, 3, impl="interpret", block_rows=16)

    def test_unknown_impl_rejected(self, rng):
        vals = jnp.asarray(rng.integers(-5, 5, (4, 2)), jnp.int32)
        ids = jnp.zeros(4, jnp.int32)
        with pytest.raises(ValueError, match="Unknown segment_sum impl"):
            segment_sum(vals, ids, 1, impl="bogus")


@pytest.mark.skipif(
    not HAS_VMA,
    reason="this jax has no vma tracking (no jax.shard_map check_vma)")
class TestShardMapVma:
    """Pallas kernels inside shard_map(check_vma=True).

    Regression (hit on TPU by daggregate, where segment_sum auto-picks
    Pallas): pallas_call's out_shape must declare the mesh axes it varies
    over, or *tracing* fails with "vma ... must not be None". Tracing the
    real impl="pallas" path via eval_shape exercises exactly that check
    without needing Mosaic, so these run on CPU. Execution-side CPU
    coverage goes through the documented interpret→xla redirect (the
    Pallas HLO interpreter cannot replay kernel bodies under vma
    tracking); the non-interpreted on-chip run lives in
    benchmarks/tpu_pallas_smoke.py.
    """

    def _mesh(self, n):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:n]), ("shards",))

    def test_segment_sum_pallas_traces_under_shard_map(self, rng):
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(4)
        vals = jnp.ones((32, 3), jnp.float32)
        ids = jnp.zeros((32,), jnp.int32)

        def fn(v, i):
            return segment_sum(v, i, 5, impl="pallas", block_rows=8)

        sharded = jax.shard_map(
            fn, mesh=mesh, in_specs=(P("shards"), P("shards")),
            out_specs=P("shards"), check_vma=True)
        out = jax.eval_shape(sharded, vals, ids)  # raises pre-fix
        assert out.shape == (5 * 4, 3)

    def test_flash_attention_pallas_traces_under_shard_map(self, rng):
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(2)
        q, k, v = _qkv(rng, b=4, s=32, h=1, d=8)

        def fn(q, k, v):
            return flash_attention(q, k, v, impl="pallas",
                                   block_q=16, block_k=16)

        sharded = jax.shard_map(
            fn, mesh=mesh, in_specs=(P("shards"), P("shards"), P("shards")),
            out_specs=P("shards"), check_vma=True)
        out = jax.eval_shape(sharded, q, k, v)  # raises pre-fix
        assert out.shape == q.shape

    def test_segment_sum_interpret_redirects_and_matches(self, rng):
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(4)
        n = 8 * 4
        vals = jnp.asarray(rng.standard_normal((n, 3)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 5, n), jnp.int32)

        def fn(v, i):
            return segment_sum(v, i, 5, impl="interpret", block_rows=8)

        sharded = jax.shard_map(
            fn, mesh=mesh, in_specs=(P("shards"), P("shards")),
            out_specs=P("shards"), check_vma=True)
        out = jax.jit(sharded)(vals, ids)  # [5 * ndev, 3] stacked partials
        per_shard = np.asarray(out).reshape(4, 5, 3).sum(axis=0)
        ref = np.zeros((5, 3), np.float32)
        np.add.at(ref, np.asarray(ids), np.asarray(vals))
        np.testing.assert_allclose(per_shard, ref, rtol=1e-5, atol=1e-5)

    def test_interpret_redirect_covers_partial_vma(self, rng):
        # replicated q but sharded k/v: the redirect must consider every
        # input's vma, not just the first one's
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(2)
        q, k, v = _qkv(rng, b=2, s=32, h=1, d=8)

        def fn(q, k, v):
            o = flash_attention(q, k, v, impl="interpret",
                                block_q=16, block_k=16)
            return jax.lax.psum(o, "shards")

        sharded = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P(None), P(None, "shards"), P(None, "shards")),
            out_specs=P(None), check_vma=True)
        out = jax.jit(sharded)(q, k, v)  # pre-fix: interpreter vma crash
        assert out.shape == q.shape
