"""Multi-host serving-fabric suite (tier-1; marker ``fabric``;
``run-tests.sh --fabric``).

The load-bearing contracts:

- **never wrong, never dropped** — a worker crash mid-query resumes
  the query from its PERSISTED checkpoint on a survivor, re-dispatching
  only the blocks the dead worker never finished, bit-identical to an
  undisturbed run; a checkpoint whose stream tag/total no longer match
  discards to a cold re-run (the PR 13 contract, now cross-process);
- **warm restarts** — a rolling restart of EVERY worker loses zero
  queries and keeps the plan-fingerprint result cache warm from the
  durable tier (zero-dispatch hits, counted separately as
  ``result_cache_warm_hits``);
- **explainable placement** — every place/re-place/rebalance decision
  lands in the flight ring, so ``tft.why("tenant:x")`` reconstructs a
  tenant's placement history with ``TFT_TRACE`` off;
- **single-process parity** — ``TFT_FABRIC=0`` is bit-identical to the
  plain scheduler path.

Heartbeat/lease wall-clock bounds ride the ``timing`` lane with
``timing_margin``; everything else avoids hard timing asserts.
"""

import json
import os
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import io as tio
from tensorframes_tpu.memory import checkpoint as _checkpoint
from tensorframes_tpu.memory import persist as _persist
from tensorframes_tpu.observability import flight as _flight
from tensorframes_tpu.observability.slo import clear_slos, set_slo
from tensorframes_tpu.plan import adaptive as _adaptive
from tensorframes_tpu.resilience import WorkerLost, faults, is_worker_lost
from tensorframes_tpu.serve import ServeFabric, live_fabric, serve_report
from tensorframes_tpu.serve.fabric import fabric_enabled
from tensorframes_tpu.utils.tracing import counters

from conftest import timing_margin

pytestmark = pytest.mark.fabric

# shared across forcings: the result-cache fingerprint is keyed on the
# computation OBJECT for in-memory identity, and on its structural
# signature for the portable (cross-process) form — a fresh lambda per
# call would defeat both
DOUBLE = lambda x: {"y": x * 2.0}  # noqa: E731
PLUS1 = lambda x: {"y": x + 1.0}  # noqa: E731


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.delenv("TFT_FABRIC", raising=False)
    monkeypatch.delenv("TFT_FABRIC_WORKERS", raising=False)
    monkeypatch.delenv("TFT_PERSIST_DIR", raising=False)
    faults.reset()
    clear_slos()
    _adaptive.invalidate_results()
    prev = _persist.configure(None)
    yield
    _persist.configure(prev)
    faults.reset()
    clear_slos()
    _adaptive.invalidate_results()


def _col(frame, name="y"):
    return np.concatenate(
        [np.asarray(b.columns[name]) for b in frame.blocks()])


def _drain(fab):
    for _ in range(3):
        fab.tick()


# ---------------------------------------------------------------------------
# durable tier: unit round-trips
# ---------------------------------------------------------------------------

def test_persist_checkpoint_roundtrip(tmp_path):
    _persist.configure(str(tmp_path))
    cp = _checkpoint.QueryCheckpoint("q-rt")
    blocks = [{"x": np.arange(4.0)}, {"x": np.arange(4.0, 8.0)}]
    cp.park_stream(iter(blocks), total=4, tag="tag-a")
    assert _persist.stats()["checkpoints"] == 1
    cp.free()  # process memory dies; disk must not
    assert _persist.stats()["checkpoints"] == 1
    back = _persist.load_checkpoint("q-rt")
    assert back is not None and back.parked_blocks == 2
    vals = list(back.resume_stream(total=4, tag="tag-a"))
    assert len(vals) == 2
    np.testing.assert_array_equal(
        np.asarray(vals[0]["x"]), np.arange(4.0))


def test_persist_checkpoint_tag_mismatch_discards(tmp_path):
    _persist.configure(str(tmp_path))
    cp = _checkpoint.QueryCheckpoint("q-mm")
    cp.park_stream(iter([{"x": np.arange(4.0)}]), total=3, tag="tag-a")
    back = _persist.load_checkpoint("q-mm")
    # the PR 13 contract, now cross-process: a drifted stream identity
    # means the parked blocks describe a different query — discard
    assert back.resume_stream(total=3, tag="tag-B") is None
    assert back.parked_blocks == 0


def test_persist_corrupt_checkpoint_is_cold_rerun(tmp_path):
    _persist.configure(str(tmp_path))
    cp = _checkpoint.QueryCheckpoint("q-corrupt")
    cp.park_stream(iter([{"x": np.arange(4.0)}]), total=1, tag="t")
    files = list((tmp_path / "checkpoints").iterdir())
    assert len(files) == 1
    files[0].write_bytes(b"not a pickle")
    assert _persist.load_checkpoint("q-corrupt") is None
    assert _persist.stats()["checkpoints"] == 0  # corrupt file removed


def test_persist_result_budget_sweep(tmp_path, monkeypatch):
    _persist.configure(str(tmp_path))
    blocks = [{"x": np.arange(256.0)}]
    _persist.save_result("fp-old", blocks)
    size = _persist.stats()["result_bytes"]
    # budget fits ~2 entries: writing a 3rd sweeps the oldest
    monkeypatch.setenv("TFT_PERSIST_RESULT_BYTES", str(int(size * 2.5)))
    time.sleep(0.02)  # mtime ordering
    _persist.save_result("fp-mid", blocks)
    time.sleep(0.02)
    _persist.save_result("fp-new", blocks)
    assert _persist.load_result("fp-old") is None
    assert _persist.load_result("fp-new") is not None


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_worker_lost_classified():
    e = WorkerLost("worker process died")
    assert is_worker_lost(e)
    from tensorframes_tpu.resilience import error_kind, is_transient
    assert error_kind(e) == "worker_lost"
    assert not is_transient(e)


# ---------------------------------------------------------------------------
# the fabric: placement + basic serving
# ---------------------------------------------------------------------------

def test_fabric_places_tenants_least_loaded(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="place") as fab:
        f = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        fab.submit(f, DOUBLE, tenant="a").result(timeout=30)
        fab.submit(f, DOUBLE, tenant="b").result(timeout=30)
        snap = fab.health_snapshot()
        assert snap["placement"]["a"] != snap["placement"]["b"]
        # sticky: a's second query lands on a's worker
        before = snap["placement"]["a"]
        fab.submit(f, DOUBLE, tenant="a").result(timeout=30)
        assert fab.health_snapshot()["placement"]["a"] == before
        assert live_fabric() is fab
    assert live_fabric() is None


def test_fabric_result_bit_identical_to_plain_scheduler(tmp_path):
    f = tft.frame({"x": np.arange(32.0)}, num_partitions=4)
    from tensorframes_tpu.serve import QueryScheduler
    with QueryScheduler(workers=1, name="plain") as sched:
        plain = _col(sched.submit(f, DOUBLE).result(timeout=30))
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="fabeq") as fab:
        fabbed = _col(fab.submit(f, DOUBLE, tenant="a").result(timeout=30))
    np.testing.assert_array_equal(plain, fabbed)


def test_fabric_disabled_single_process_path(tmp_path, monkeypatch):
    monkeypatch.setenv("TFT_FABRIC", "0")
    assert not fabric_enabled()
    with ServeFabric(workers=4, monitor=False,
                     persist_dir=str(tmp_path), name="off") as fab:
        assert len(fab._workers) == 1  # collapses regardless of ask
        f = tft.frame({"x": np.arange(16.0)}, num_partitions=4)
        got = _col(fab.submit(f, DOUBLE, tenant="a").result(timeout=30))
        np.testing.assert_array_equal(got, np.arange(16.0) * 2.0)


# ---------------------------------------------------------------------------
# worker crash: the failure matrix
# ---------------------------------------------------------------------------

def test_worker_crash_mid_query_resumes_elsewhere_bit_identical(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="crash") as fab:
        f = tft.frame({"x": np.arange(64.0)}, num_partitions=8)
        faults.arm("worker", fail_n=1)
        fq = fab.submit(f, DOUBLE, tenant="alice")
        got = _col(fq.result(timeout=60))
        np.testing.assert_array_equal(got, np.arange(64.0) * 2.0)
        assert fq.attempts == 2  # original + one re-dispatch
        snap = fab.health_snapshot()
        assert snap["lost"] == 1 and snap["live"] == 1
        # the survivor resumed from the PERSISTED checkpoint: the
        # resume re-dispatched fewer blocks than the query has
        chain = tft.why(fq.query_id)
        assert "fabric.resume_dispatch" in chain
        assert "resume from the persisted checkpoint" in chain
        assert "preempt.park" in chain  # the crash-side park
        recs = [r for r in _flight.for_query(fq.query_id)
                if r["kind"] == "fabric.resume_dispatch"]
        assert recs and recs[0]["from_checkpoint"]
        assert 0 < recs[0]["resumed_blocks"] < 8
        # and the tenant was re-placed off the corpse
        assert "fabric.replace" in tft.why("tenant:alice")


def test_worker_crash_discarded_checkpoint_cold_rerun(tmp_path):
    """A checkpoint that does not survive (deleted under the fabric)
    degrades to a cold re-run on the survivor — same answer."""
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="cold") as fab:
        f = tft.frame({"x": np.arange(64.0)}, num_partitions=8)
        real_load = _persist.load_checkpoint
        _persist.load_checkpoint = lambda qid: None  # disk wiped
        try:
            faults.arm("worker", fail_n=1)
            fq = fab.submit(f, DOUBLE, tenant="a")
            got = _col(fq.result(timeout=60))
        finally:
            _persist.load_checkpoint = real_load
        np.testing.assert_array_equal(got, np.arange(64.0) * 2.0)
        recs = [r for r in _flight.for_query(fq.query_id)
                if r["kind"] == "fabric.resume_dispatch"]
        assert recs and not recs[0]["from_checkpoint"]


def test_idle_worker_fault_consumed_at_heartbeat(tmp_path):
    """`TFT_FAULTS=worker:1` with NO running query: the next heartbeat
    consumes the fault, the lease expires, the worker is declared lost
    — and serving continues on the survivor."""
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="idle") as fab:
        faults.arm("worker", fail_n=1)
        for _ in range(fab.missed_hb + 2):
            fab.tick()
        snap = fab.health_snapshot()
        assert snap["lost"] == 1
        assert not faults.active("worker")  # consumed
        f = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        got = _col(fab.submit(f, DOUBLE, tenant="a").result(timeout=30))
        np.testing.assert_array_equal(got, np.arange(8.0) * 2.0)


def test_queued_queries_replaced_not_dropped(tmp_path):
    """Queries still QUEUED on a crashed worker re-place and re-run
    cold: zero lost, zero duplicated."""
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="queue") as fab:
        f = tft.frame({"x": np.arange(16.0)}, num_partitions=2)
        fab.submit(f, DOUBLE, tenant="a").result(timeout=30)
        widx = fab._placement["a"]
        # pile queries onto a's worker, then kill it before they drain
        fqs = [fab.submit(f, PLUS1, tenant="a") for _ in range(3)]
        fab._workers[widx].fault_pending = True
        fab._workers[widx].scheduler.mark_lost()
        outs = [_col(fq.result(timeout=60)) for fq in fqs]
        for got in outs:
            np.testing.assert_array_equal(got, np.arange(16.0) + 1.0)
        assert all(fq.done() and fq.error is None for fq in fqs)


def test_no_survivors_is_classified_worker_lost(tmp_path):
    with ServeFabric(workers=1, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="alone") as fab:
        f = tft.frame({"x": np.arange(16.0)}, num_partitions=4)
        faults.arm("worker", fail_n=1)
        fq = fab.submit(f, DOUBLE, tenant="a")
        with pytest.raises(WorkerLost):
            fq.result(timeout=60)


# ---------------------------------------------------------------------------
# durable result cache across restarts
# ---------------------------------------------------------------------------

def test_rolling_restart_keeps_result_cache_warm(tmp_path):
    pq = str(tmp_path / "t.parquet")
    tio.write_parquet(
        tft.frame({"x": np.arange(32.0)}, num_partitions=4), pq)
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path / "persist"),
                     name="roll") as fab:
        f = tio.read_parquet(pq)
        # two sightings admit (two-touch), the admit persists
        outs = [fab.submit(f, DOUBLE, tenant="t1").result(timeout=30)
                for _ in range(2)]
        a = _col(outs[0])
        assert counters.get("persist.result_writes") >= 1
        warm0 = counters.get("plan.result_cache_warm_hits")
        # restart EVERY worker: in-memory caches die with each epoch
        assert fab.rolling_restart() == 2
        assert all(w.epoch == 1 for w in fab._workers)
        dispatches0 = counters.get("pipeline.dispatches")
        got = _col(fab.submit(f, DOUBLE, tenant="t1").result(timeout=30))
        np.testing.assert_array_equal(a, got)
        # served WARM from the durable tier: counted separately, and
        # with zero new pipeline dispatches
        assert counters.get("plan.result_cache_warm_hits") == warm0 + 1
        assert counters.get("pipeline.dispatches") == dispatches0
        # warm hit re-admits into memory: the NEXT hit is a plain hit
        hits0 = counters.get("plan.result_cache_hits")
        fab.submit(f, DOUBLE, tenant="t1").result(timeout=30)
        assert counters.get("plan.result_cache_hits") == hits0 + 1


def test_rolling_restart_loses_zero_inflight_queries(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="migrate") as fab:
        f = tft.frame({"x": np.arange(24.0)}, num_partitions=3)
        fqs = [fab.submit(f, PLUS1, tenant=t)
               for t in ("a", "b", "c")]
        assert fab.rolling_restart() == 2
        for fq in fqs:
            got = _col(fq.result(timeout=60))
            np.testing.assert_array_equal(got, np.arange(24.0) + 1.0)
        assert "fabric.worker_restart" in tft.why(fqs[0].query_id) or \
            counters.get("fabric.worker_restarts") >= 2


def test_restart_probe_gates_admission(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="gate") as fab:
        w = fab._workers[0]
        ok = fab.restart_worker(0)
        assert ok and w.epoch == 1 and w.alive
        assert any(r["kind"] == "fabric.admit"
                   for r in _flight.recent()
                   if r.get("worker") == "w0")


def test_shared_compile_cache_spans_workers(tmp_path):
    """One SharedCompileCache instance serves every worker and every
    epoch: tenant B's identical computation on another worker hits."""
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="cc") as fab:
        f = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        # structurally identical but DISTINCT computation objects: the
        # interner's per-object short-circuit must not mask the test
        fab.submit(f, lambda x: {"y": x * 2.0},
                   tenant="a").result(timeout=30)
        fab.submit(f, lambda x: {"y": x * 2.0},
                   tenant="b").result(timeout=30)
        assert fab.health_snapshot()["placement"]["a"] != \
            fab.health_snapshot()["placement"]["b"]
        st = fab.compile_cache.stats()
        assert st["hits"] >= 1
        for w in fab._workers:
            assert w.scheduler.compile_cache is fab.compile_cache


# ---------------------------------------------------------------------------
# SLO-burn re-placement
# ---------------------------------------------------------------------------

def test_slo_burn_replaces_hot_tenant(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="burn") as fab:
        f = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        set_slo("hot", objective_ms=0.0001)     # impossible: burns
        set_slo("cool", objective_ms=60000.0)   # trivially met
        for fn in (DOUBLE, PLUS1, DOUBLE, PLUS1):
            fab.submit(f, fn, tenant="hot").result(timeout=30)
            fab.submit(f, fn, tenant="cool").result(timeout=30)
        before = dict(fab._placement)
        for _ in range(3 * fab.rebalance_ticks):
            fab.tick()
        after = dict(fab._placement)
        assert before["hot"] != after["hot"]
        assert before["cool"] == after["cool"]
        # observable via tft.why(), tracing off
        chain = tft.why("tenant:hot")
        assert "fabric.rebalance" in chain and "SLO burn" in chain
        # stale evidence never ping-pongs: more ticks, no new queries
        for _ in range(4 * fab.rebalance_ticks):
            fab.tick()
        assert fab._placement["hot"] == after["hot"]


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------

def test_health_and_doctor_show_fabric(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="obs") as fab:
        f = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        fab.submit(f, DOUBLE, tenant="a").result(timeout=30)
        h = tft.health()
        assert h["fabric"]["running"] and h["fabric"]["workers"] == 2
        assert h["fabric"]["persist"]["enabled"]
        assert "fabric" in tft.doctor()
        rep = serve_report(fab._workers[0].scheduler)
        assert "placement" in rep
    assert not tft.health()["fabric"].get("running", False)


def test_lost_worker_raises_health_warning(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="warn") as fab:
        faults.arm("worker", fail_n=1)
        for _ in range(fab.missed_hb + 2):
            fab.tick()
        warns = tft.health()["warnings"]
        assert any("worker(s) declared lost" in w for w in warns)


def test_flight_records_carry_worker_and_dumps_merge(tmp_path):
    with ServeFabric(workers=2, monitor=False, probe=False,
                     persist_dir=str(tmp_path), name="wid") as fab:
        f = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        fq = fab.submit(f, DOUBLE, tenant="a")
        fq.result(timeout=30)
        recs = _flight.for_query(fq.query_id)
        workers = {r.get("worker") for r in recs if r.get("worker")}
        assert workers <= {"w0", "w1"} and workers
    # per-worker dumps: header carries worker=, records re-attribute
    p0 = str(tmp_path / "w0.jsonl")
    _flight.dump(p0, reason="test", worker="w0")
    with open(p0) as fh:
        head = json.loads(fh.readline())
    assert head["worker"] == "w0"
    merged = _flight.load_dumps([p0])
    assert merged and all(r.get("worker") for r in merged)


def test_doctor_merges_per_worker_dumps(tmp_path):
    _flight.record("serve.shed", tenant="t", est_bytes=1, headroom=0,
                   budget_s=1)
    p = str(tmp_path / "wX.jsonl")
    _flight.dump(p, reason="test", worker="wX")
    d = tft.doctor(flight_dumps=[p])
    assert "per-worker dump" in d and "w=wX" in d


# ---------------------------------------------------------------------------
# the worker fault site without a fabric: park + same-process resume
# ---------------------------------------------------------------------------

def test_worker_fault_site_without_fabric_still_completes():
    """No fabric: the `worker` site parks the query and the SAME
    scheduler resumes it (there is no coordinator to kill the process),
    so the site degrades to a preempt/resume — never a wrong answer."""
    from tensorframes_tpu.serve import QueryScheduler
    with QueryScheduler(workers=1, name="solo") as sched:
        f = tft.frame({"x": np.arange(32.0)}, num_partitions=4)
        faults.arm("worker", fail_n=1)
        q = sched.submit(f, DOUBLE)
        got = _col(q.result(timeout=30))
        np.testing.assert_array_equal(got, np.arange(32.0) * 2.0)
        assert q.preemptions >= 1


# ---------------------------------------------------------------------------
# timing lane: heartbeat/lease wall-clock bounds
# ---------------------------------------------------------------------------

@pytest.mark.timing
def test_monitor_declares_lost_within_lease_bound(tmp_path):
    hb_ms = 20.0
    with ServeFabric(workers=2, monitor=True, probe=False,
                     heartbeat_ms=hb_ms, missed_hb=3,
                     persist_dir=str(tmp_path), name="lease") as fab:
        faults.arm("worker", fail_n=1)
        # lease math: fault consumed on a beat, lost after 3 misses —
        # generously 20 beat intervals, margin-scaled
        deadline = time.monotonic() + timing_margin(
            20 * (hb_ms / 1000.0) + 1.0)
        while time.monotonic() < deadline:
            if fab.health_snapshot()["lost"] == 1:
                break
            time.sleep(hb_ms / 1000.0)
        assert fab.health_snapshot()["lost"] == 1
