"""Pipeline (pp) and expert (ep) parallelism on the 8-virtual-device mesh.

These axes have no reference analogue (SURVEY.md §2.3: Spark partitions
only); correctness is defined against the unsharded single-device math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tensorframes_tpu.models.transformer import (TransformerConfig,
                                                 TransformerLM)
from tensorframes_tpu.parallel.mesh import DeviceMesh
from tensorframes_tpu.parallel.moe import init_switch_ffn, switch_ffn
from tensorframes_tpu.parallel.pipeline import pipeline_apply


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return DeviceMesh(Mesh(devs, names), data_axis=names[0])


# -- switch_ffn -------------------------------------------------------------

def _ref_switch(x, params, capacity):
    """Token-at-a-time top-1 routing with capacity drops (same gelu as the
    kernel: jax.nn.gelu's default tanh approximation)."""
    logits = x @ params["router"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    gates = e / e.sum(-1, keepdims=True)
    top = gates.argmax(-1)
    out = np.zeros_like(x)
    counts = {}
    for t in range(x.shape[0]):
        ex = int(top[t])
        k = counts.get(ex, 0)
        if k < capacity:
            counts[ex] = k + 1
            h = np.asarray(jax.nn.gelu(x[t] @ params["w1"][ex]))
            out[t] = (h @ params["w2"][ex]) * gates[t, ex]
    return out


def test_switch_ffn_routes_and_drops():
    rng = jax.random.PRNGKey(0)
    T, D, F, E = 32, 8, 16, 4
    params = init_switch_ffn(rng, D, F, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, D))
    out, aux = switch_ffn(x, params, capacity_factor=1.0)
    np_params = jax.tree_util.tree_map(np.asarray, params)
    ref = _ref_switch(np.asarray(x), np_params, capacity=T // E)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
    assert float(aux) > 0.0


def test_switch_ffn_sharded_matches_unsharded():
    mesh = _mesh((2, 4), ("data", "expert"))
    rng = jax.random.PRNGKey(0)
    T, D, F, E = 64, 8, 16, 4
    params = init_switch_ffn(rng, D, F, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, D))
    ref, _ = switch_ffn(x, params, capacity_factor=1.25)
    out, _ = jax.jit(lambda x, p: switch_ffn(
        x, p, capacity_factor=1.25, mesh=mesh, expert_axis="expert"))(
            x, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# -- pipeline_apply ---------------------------------------------------------

def test_pipeline_matches_sequential():
    mesh = _mesh((2, 4), ("data", "pipe"))
    P_, per = 4, 3
    D = 6
    rng = np.random.default_rng(0)
    ws = jnp.asarray(rng.normal(0, 0.3, (P_, D, D)), jnp.float32)

    def stage_fn(w, act):
        return jnp.tanh(act @ w[0])

    x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)
    got = pipeline_apply(stage_fn, ws[:, None], x, mesh, pipe_axis="pipe",
                         data_axis="data")
    want = x
    for i in range(P_):
        want = jnp.tanh(want @ ws[i])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_microbatches_more_than_stages():
    mesh = _mesh((1, 4), ("data", "pipe"))
    D = 4
    rng = np.random.default_rng(1)
    ws = jnp.asarray(rng.normal(0, 0.3, (4, D, D)), jnp.float32)

    def stage_fn(w, act):
        return act @ w[0]

    x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)
    got = pipeline_apply(stage_fn, ws[:, None], x, mesh, pipe_axis="pipe",
                         num_microbatches=8)
    want = x @ ws[0] @ ws[1] @ ws[2] @ ws[3]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_is_differentiable():
    mesh = _mesh((1, 4), ("data", "pipe"))
    D = 4
    ws = jnp.ones((4, 1, D, D), jnp.float32) * 0.1
    x = jnp.ones((4, D), jnp.float32)

    def loss(w):
        return pipeline_apply(lambda wp, a: a @ wp[0], w, x, mesh,
                              pipe_axis="pipe").sum()

    g = jax.grad(loss)(ws)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


# -- transformer integration ------------------------------------------------

@pytest.fixture(scope="module")
def moe_model():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, num_experts=4)
    return TransformerLM(cfg)


def test_moe_transformer_forward_and_loss(moe_model):
    params = moe_model.init(jax.random.PRNGKey(0))
    toks = jnp.zeros((2, 8), jnp.int32)
    logits, aux = moe_model.apply_with_aux(params, toks)
    assert logits.shape == (2, 8, 64)
    assert float(aux) > 0.0  # 2 MoE layers contribute
    loss = moe_model.loss(params, toks, jnp.ones((2, 8), jnp.int32))
    assert np.isfinite(float(loss))


def test_moe_expert_parallel_train_step(moe_model):
    mesh = _mesh((2, 2, 2), ("data", "model", "expert"))
    step, init_state = moe_model.make_sharded_train_step(
        mesh, data_axis="data", model_axis="model", expert_axis="expert")
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 64,
                              jnp.int32)
    state, loss = step(state, toks, jnp.roll(toks, -1, 1))
    assert np.isfinite(float(loss))


def test_pipelined_train_step_runs_and_learns():
    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                            n_layers=4, d_ff=32)
    model = TransformerLM(cfg)
    mesh = _mesh((2, 4), ("data", "pipe"))
    step, init_state = model.make_pipelined_train_step(
        mesh, pipe_axis="pipe", data_axis="data", learning_rate=1e-2)
    state = init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0, 32,
                              jnp.int32)
    tgts = jnp.roll(toks, -1, 1)
    state, l0 = step(state, toks, tgts)  # state is donated: use the return
    for _ in range(5):
        state, l = step(state, toks, tgts)
    assert float(l) < float(l0)  # the pipelined grads actually descend


def test_pipelined_forward_matches_unpipelined():
    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                            n_layers=4, d_ff=32)
    model = TransformerLM(cfg)
    mesh = _mesh((1, 4), ("data", "pipe"))
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32,
                              jnp.int32)
    want = model.apply(params, toks)

    # rebuild the pipelined forward exactly as the train step does
    step, init_state = model.make_pipelined_train_step(
        mesh, pipe_axis="pipe", data_axis="data")
    state = init_state(jax.random.PRNGKey(0))
    # loss equality is the cleanest observable: same params, same tokens
    tgts = jnp.roll(toks, -1, 1)
    _, pipel = step(state, toks, tgts)
    ref_loss = model.loss(params, toks, tgts)
    assert float(pipel) == pytest.approx(float(ref_loss), rel=2e-4)


def test_pipelined_step_rejects_moe(moe_model):
    mesh = _mesh((1, 4), ("data", "pipe"))
    with pytest.raises(ValueError, match="dense FFN models only"):
        moe_model.make_pipelined_train_step(mesh, pipe_axis="pipe")
