"""The demos are acceptance workloads (SURVEY.md §2.5): each must run at
small scale and produce verifiably correct numbers against plain numpy."""

import numpy as np
import pytest

import tensorframes_tpu as tft

from demos import geom_mean as gm
from demos import groupby_scratch as gs
from demos import kmeans as km


# -- kmeans -----------------------------------------------------------------

@pytest.fixture(scope="module")
def km_data():
    return km.make_data(n=200, num_features=3, k=2, num_partitions=3)


def _numpy_step(pts, centers):
    d = ((pts[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    idx = d.argmin(1)
    new = np.stack([
        pts[idx == j].mean(0) if (idx == j).any() else centers[j]
        for j in range(centers.shape[0])])
    return new, float(d.min(1).sum())


@pytest.mark.parametrize("step", [km.step_aggregate, km.step_preaggregate],
                         ids=["aggregate", "preaggregate"])
def test_kmeans_step_matches_numpy(km_data, step):
    df, init, _ = km_data
    pts = np.concatenate([b.dense("features") for b in df.blocks()])
    got_c, got_d = step(df, init)
    want_c, want_d = _numpy_step(pts, init)
    np.testing.assert_allclose(got_c, want_c, rtol=1e-5)
    assert got_d == pytest.approx(want_d, rel=1e-5)


def test_kmeans_converges_to_true_centers(km_data):
    df, init, true_centers = km_data
    centers, history = km.kmeans(df, init, num_iters=30)
    assert history == sorted(history, reverse=True)  # monotone improvement
    # each true center has a learned center within the blob radius
    for t in true_centers:
        assert np.linalg.norm(centers - t, axis=1).min() < 0.5


def test_kmeans_device_resident_step_matches(km_data):
    from tensorframes_tpu.parallel.distributed import distribute
    from tensorframes_tpu.parallel.mesh import local_mesh

    df, init, _ = km_data
    pts = np.concatenate([b.dense("features") for b in df.blocks()])
    dist = distribute(df, local_mesh())
    got_c, got_d = km.step_device_resident(dist, init)
    want_c, want_d = _numpy_step(pts, init)
    np.testing.assert_allclose(got_c, want_c, rtol=1e-5)
    assert got_d == pytest.approx(want_d, rel=1e-5)


def test_kmeans_native_resident_loop_matches(km_data, monkeypatch):
    # the whole iteration loop in the native C++ core with device-held
    # loop state (variant E) must match iterating the numpy step
    from tensorframes_tpu import native_pjrt
    from tensorframes_tpu.parallel.distributed import distribute
    from tensorframes_tpu.parallel.mesh import local_mesh

    if not native_pjrt.available():
        pytest.skip("libtfrpjrt.so not built")
    monkeypatch.setenv("TFT_EXECUTOR", "pjrt")
    df, init, _ = km_data
    pts = np.concatenate([b.dense("features") for b in df.blocks()])
    dist = distribute(df, local_mesh(4))
    iters = 7
    got = km.kmeans_native_resident(dist, init, num_iters=iters)
    want = np.asarray(init, np.float64)
    for _ in range(iters):
        want, _ = _numpy_step(pts, want)
    np.testing.assert_allclose(got, want, rtol=1e-6)


# -- harmonic / geometric mean ----------------------------------------------

def test_harmonic_mean_per_key():
    df = gm.make_data(n=30)
    rows = gm.harmonic_mean_per_key(df).collect()
    x = np.concatenate([b.dense("x") for b in df.blocks()])
    keys = np.concatenate(
        [np.asarray([c for c in b.columns["key"]]) for b in df.blocks()])
    got = {r["key"]: r["harmonic_mean"] for r in rows}
    assert set(got) == {"g0", "g1", "g2"}
    for g in got:
        grp = x[keys == g]
        want = len(grp) / (1.0 / grp).sum()
        assert got[g] == pytest.approx(want, rel=1e-6)


def test_geometric_mean_per_key():
    df = gm.make_data(n=30)
    rows = gm.geometric_mean_per_key(df).collect()
    x = np.concatenate([b.dense("x") for b in df.blocks()])
    keys = np.concatenate(
        [np.asarray([c for c in b.columns["key"]]) for b in df.blocks()])
    for r in rows:
        grp = x[keys == r["key"]]
        want = np.exp(np.log(grp).mean())
        assert r["geometric_mean"] == pytest.approx(want, rel=1e-6)


def test_string_key_and_unused_column_ride_along():
    # the two reference-found bugs: a string column in the frame, and a
    # numeric column unused by the computation — both must pass through
    df = gm.make_data(n=12)
    out = tft.map_blocks(lambda x: {"y": x * 2.0}, df)
    rows = out.collect()
    assert rows[0].fields == ("key", "x", "y")
    assert isinstance(rows[0]["key"], str)


# -- groupby scratch + README examples --------------------------------------

def test_groupby_sum():
    rows = gs.groupby_sum()
    # keys: 1,2 -> '0'; 3,4,5 -> '1'
    assert [(r["key"], r["x"]) for r in rows] == [("0", 3.0), ("1", 12.0)]


def test_readme_map_blocks():
    rows = gs.readme_map_blocks()
    assert [r["z"] for r in rows] == [3.0, 4.0, 5.0, 6.0, 7.0]


def test_readme_reduce_vector():
    s, m = gs.readme_reduce_vector()
    np.testing.assert_allclose(s, [3.0, 3.0])
    np.testing.assert_allclose(m, [1.0, 1.0])


def test_readme_dsl_map():
    rows = gs.readme_dsl_map()
    np.testing.assert_allclose([r["z"] for r in rows],
                               np.arange(5.0) * 0.1 + 3.0)


def test_kmeans_daggregate_step_matches(km_data):
    # variant D: the groupBy shuffle at mesh scale (device-side keys)
    from tensorframes_tpu.parallel.distributed import distribute
    from tensorframes_tpu.parallel.mesh import local_mesh

    df, init, _ = km_data
    pts = np.concatenate([b.dense("features") for b in df.blocks()])
    dist = distribute(df, local_mesh())
    got_c, got_d = km.step_daggregate(dist, init)
    want_c, want_d = _numpy_step(pts, init)
    np.testing.assert_allclose(got_c, want_c, rtol=1e-5)
    assert got_d == pytest.approx(want_d, rel=1e-5)


# -- LM training loop (frames as data path + mesh step + checkpoint) --------

def test_train_lm_learns_and_resumes(tmp_path):
    from demos import train_lm as tl
    from tensorframes_tpu.parallel.mesh import local_mesh

    mesh = local_mesh()
    root = str(tmp_path / "ckpt")
    kw = dict(batch=8, seq_len=16, vocab=32,
              checkpoint_root=root, checkpoint_every=4)

    _, losses = tl.train(mesh, n_steps=8, **kw)
    assert len(losses) == 8
    assert losses[-1] < losses[0]          # it learns

    # resume from the step-8 checkpoint; only steps 8..12 run
    _, more = tl.train(mesh, n_steps=12, resume=True, **kw)
    assert len(more) == 4

    # uninterrupted reference run over the same schedule, fresh root
    _, full = tl.train(mesh, n_steps=12, batch=8, seq_len=16, vocab=32,
                       checkpoint_root=str(tmp_path / "ckpt2"),
                       checkpoint_every=100)
    np.testing.assert_allclose(more, full[8:], rtol=1e-4, atol=1e-5)


def test_train_lm_corpus_is_frame_partitioned():
    from demos import train_lm as tl

    df = tl.corpus_frame(n_batches=3, batch=4, seq_len=8, vocab=16)
    blocks = df.blocks()
    assert len(blocks) == 3
    toks = blocks[0].dense("tokens")
    assert toks.shape == (4, 9)
    # modular-increment property: constant per-row step of 1 or 2
    diffs = np.diff(toks, axis=1) % 16
    assert set(np.unique(diffs)) <= {1, 2}
    assert (diffs == diffs[:, :1]).all()


# -- analytics pipeline (csv -> filter -> mesh aggregate -> rank) -----------

def test_analytics_pipeline_matches_numpy(tmp_path):
    from demos import analytics as an

    csv_path = str(tmp_path / "readings.csv")
    an.make_csv(csv_path, n=3000, sites=3, sensors=4, seed=5)
    ranked = an.pipeline(csv_path)
    rows = ranked.collect()

    # numpy recomputation from the raw file
    raw = np.genfromtxt(csv_path, delimiter=",", names=True)
    keep = raw["value"] >= 0
    ref = {}
    for s, d, v in zip(raw["site"][keep].astype(int),
                       raw["sensor"][keep].astype(int),
                       raw["value"][keep]):
        ref[(s, d)] = ref.get((s, d), 0.0) + v
    got = {(r["site"], r["sensor"]): r["value"] for r in rows}
    assert set(got) == set(ref)
    for k in ref:
        assert got[k] == pytest.approx(ref[k], rel=1e-5)
    totals = [r["value"] for r in rows]
    assert totals == sorted(totals, reverse=True)
