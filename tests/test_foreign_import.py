"""Foreign-graph import: bare StableHLO modules as computations.

The reference accepted computations authored by an alien stack — real TF
Python serialized a GraphDef and the engine ran it (``core.py:37-40``,
``TensorFlowOps.scala:46-52``). The analogue here: a module produced by
ANY exporter (plain ``jax.jit(...).lower()``, not this library's
``serialize``) enters through ``builder.map_blocks_builder`` with explicit
specs and runs on both executors.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import tensorframes_tpu as tft
from tensorframes_tpu import builder, dtypes as _dt
from tensorframes_tpu.computation import Computation, TensorSpec
from tensorframes_tpu.engine import ops as _ops
from tensorframes_tpu.shape import Shape


def _foreign_module_text(n=6, dtype=jnp.float64):
    """A module this library did NOT produce: plain jax.jit lowering."""
    fn = lambda x: x * 2.0 + 1.0  # noqa: E731
    return jax.jit(fn).lower(
        jax.ShapeDtypeStruct((n,), dtype)).as_text()


class TestFromStablehlo:
    def test_through_map_blocks_builder(self):
        text = _foreign_module_text()
        df = tft.frame({"x": np.arange(6.0)}, num_partitions=1)
        out = (builder.map_blocks_builder(df)
               .graph(text.encode())
               .signature([TensorSpec("x", _dt.double, Shape(6))],
                          [TensorSpec("z", _dt.double, Shape(6))])
               .build())
        rows = out.collect()
        assert [r["z"] for r in rows] == [v * 2.0 + 1.0
                                          for v in np.arange(6.0)]
        # inputs ride along untrimmed, like any map_blocks
        assert [r["x"] for r in rows] == list(np.arange(6.0))

    def test_outputs_inferred_from_module(self):
        text = _foreign_module_text()
        comp = Computation.from_stablehlo(
            text, [TensorSpec("x", _dt.double, Shape(6))])
        assert comp.output_names == ["out_0"]
        assert comp.outputs[0].shape.dims == (6,)
        df = tft.frame({"x": np.arange(6.0)}, num_partitions=1)
        rows = _ops.map_blocks(comp, df, trim=True).collect()
        assert [r["out_0"] for r in rows] == [v * 2.0 + 1.0
                                              for v in np.arange(6.0)]

    def test_composes_under_jit(self):
        # exported-call computations must stay traceable (the engine jits
        # comp.fn; the mesh layer may jit it inside larger programs)
        comp = Computation.from_stablehlo(
            _foreign_module_text(),
            [TensorSpec("x", _dt.double, Shape(6))],
            [TensorSpec("z", _dt.double, Shape(6))])
        f = jax.jit(lambda d: comp.fn(d)["z"] + 1.0)
        got = f({"x": jnp.arange(6.0)})
        np.testing.assert_allclose(np.asarray(got),
                                   np.arange(6.0) * 2.0 + 2.0)

    def test_unknown_dims_rejected(self):
        from tensorframes_tpu.shape import Unknown

        with pytest.raises(ValueError, match="unknown dims"):
            Computation.from_stablehlo(
                _foreign_module_text(),
                [TensorSpec("x", _dt.double, Shape(Unknown))])

    def test_bare_module_without_signature_errors(self):
        df = tft.frame({"x": np.arange(6.0)}, num_partitions=1)
        b = builder.map_blocks_builder(df).graph(
            _foreign_module_text().encode())
        with pytest.raises(ValueError, match="signature"):
            b.build()

    def test_garbage_bytes_still_canonical_error(self):
        df = tft.frame({"x": np.arange(6.0)}, num_partitions=1)
        with pytest.raises(ValueError, match="Not a serialized"):
            builder.map_blocks_builder(df).graph(b"\x00\x01garbage")


class TestForeignOnNativeExecutor:
    @pytest.fixture
    def native(self):
        from tensorframes_tpu import native_pjrt

        if not native_pjrt.available():
            pytest.skip("libtfrpjrt.so not built")
        return native_pjrt

    def test_map_blocks_via_pjrt_core(self, native):
        comp = Computation.from_stablehlo(
            _foreign_module_text(),
            [TensorSpec("x", _dt.double, Shape(6))],
            [TensorSpec("z", _dt.double, Shape(6))])
        assert comp._native_dynamic is not None  # jax-free compile path
        ex = native.PjrtBlockExecutor(backend="cpu")
        df = tft.frame({"x": np.arange(6.0)}, num_partitions=1)
        rows = _ops.map_blocks(comp, df, executor=ex).collect()
        assert [r["z"] for r in rows] == [v * 2.0 + 1.0
                                          for v in np.arange(6.0)]
