"""Tests for the utils/ observability layer (logging shim + tracing).

Reference analogues: ``Logging.scala:5-9`` (logDebug/logTrace facade) and
the self-timed perf narration replaced here by the span/timings registry
(SURVEY.md §5).
"""

import logging

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.utils import logging as tlog
from tensorframes_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _clean_state():
    was = tracing.enabled()
    tracing.timings.reset()
    root = tlog.get_logger()
    saved = (list(root.handlers), root.level, root.propagate,
             tlog._initialized, tlog._handler)
    yield
    tracing.timings.reset()
    (tracing.enable if was else tracing.disable)()
    root.handlers, root.level, root.propagate = saved[0], saved[1], saved[2]
    tlog._initialized, tlog._handler = saved[3], saved[4]


def test_get_logger_hierarchy():
    root = tlog.get_logger()
    child = tlog.get_logger("engine.executor")
    assert child.name == "tensorframes_tpu.engine.executor"
    assert root.name == "tensorframes_tpu"
    # name already qualified -> not doubled
    same = tlog.get_logger("tensorframes_tpu.engine.executor")
    assert same is child


def test_trace_level_below_debug(caplog):
    log = tlog.get_logger("t1")
    log.setLevel(tlog.TRACE)
    with caplog.at_level(tlog.TRACE, logger="tensorframes_tpu.t1"):
        log.trace("hot loop %d", 7)
    assert any(r.levelno == tlog.TRACE and "hot loop 7" in r.message
               for r in caplog.records)
    assert tlog.TRACE < logging.DEBUG


def test_initialize_logging_idempotent():
    root = tft.initialize_logging(level=logging.INFO)
    n = len(root.handlers)
    root2 = tft.initialize_logging(level=logging.WARNING)
    assert root2 is root
    assert len(root.handlers) == n  # no handler stacking
    assert root.level == logging.WARNING


def test_span_disabled_records_nothing():
    tracing.disable()
    with tracing.span("nothing"):
        pass
    assert tracing.timings.snapshot() == {}


def test_span_enabled_records_stats():
    tracing.enable()
    for _ in range(3):
        with tracing.span("stage"):
            pass
    snap = tracing.timings.snapshot()
    assert snap["stage"]["count"] == 3
    assert snap["stage"]["total_s"] >= 0.0
    assert "stage" in tracing.timings.report()


def test_engine_stages_report_spans(monkeypatch):
    # serial engine spans; the pipelined stream's spans/gauges are
    # covered by tests/test_pipeline.py
    monkeypatch.setenv("TFT_PIPELINE_DEPTH", "1")
    tracing.enable()
    df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
    out = tft.map_blocks(lambda x: {"z": x + 3.0}, df)
    out.collect()
    tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
    snap = tracing.timings.snapshot()
    assert snap["map_blocks.block"]["count"] == 2
    assert "executor.dispatch" in snap
    assert "reduce_blocks.partials" in snap


def test_report_empty_message():
    assert "no spans" in tracing.timings.report()


def test_profile_writes_trace(tmp_path):
    tracing.disable()
    with tracing.profile(str(tmp_path)):
        df = tft.frame({"x": np.arange(4.0)})
        tft.map_blocks(lambda x: {"z": x * 2.0}, df).collect()
        assert tracing.enabled()  # host spans on during the window
    assert not tracing.enabled()
    assert list(tmp_path.rglob("*"))  # something was written
    assert tracing.timings.snapshot()  # host spans captured in-window


def test_mesh_op_spans_recorded():
    # TFT_TRACE spans cover the distribution layer too
    import tensorframes_tpu as tft
    from tensorframes_tpu import parallel as par
    from tensorframes_tpu.utils import tracing

    tracing.enable()
    try:
        tracing.timings.reset()
        df = tft.frame({"k": np.arange(16, dtype=np.int32) % 3,
                        "x": np.arange(16.0)})
        dist = par.distribute(tft.analyze(df), par.local_mesh())
        par.dmap_blocks(lambda x: {"z": x + 1.0}, dist)
        par.dfilter(lambda x: x > 3.0, dist)
        par.dsort("x", dist)
        par.daggregate({"x": "sum"}, dist.select(["k", "x"]), "k")
        par.dreduce_blocks({"x": "sum"}, dist.select(["x"]))
        par.dreduce_blocks(lambda x_input: {"x": x_input.sum(0)},
                           dist.select(["x"]))
        import jax.numpy as jnp
        par.daggregate(lambda x_input: {"x": jnp.sum(x_input, 0)},
                       dist.select(["k", "x"]), "k")
        names = set(tracing.timings.snapshot())
        assert {"dmap_blocks.dispatch", "dfilter.dispatch",
                "daggregate.dispatch",
                "dreduce_blocks.collective_dispatch",
                "dreduce_blocks.generic_dispatch",
                "daggregate.segmented_fold_dispatch"} <= names, names
        # multi-shard meshes take the columnsort program; single-shard
        # (and non-tiling) frames the local argsort program
        assert names & {"dsort.columnsort_dispatch",
                        "dsort.dispatch"}, names
    finally:
        tracing.disable()
