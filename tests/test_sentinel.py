"""Performance-regression sentinel suite (tier-1; marker ``sentinel``;
``run-tests.sh --sentinel``).

The load-bearing contracts:

- the telemetry timeline is ALWAYS-ON, bounded, and opportunistic (no
  background thread): query finishes and stream batch boundaries take
  interval-gated snapshots, ``tft.timeline(family)`` answers deltas and
  rates over a window, and ``TFT_TIMELINE=0`` bypasses sampling, cost
  capture, AND regression detection bit-identically;
- every served completion assembles a cost vector (latency, compile
  delta, fused-stage wall, slot waits, spill/fault bytes, dispatches)
  keyed by the plan fingerprint, folded into a rolling EWMA + MAD
  baseline; portable (parquet-rooted) baselines round-trip through the
  ``memory/persist.py`` durable tier;
- the scripted drill: K warm runs of a fingerprinted query, then ONE
  ``TFT_FAULTS=perf:1`` slowdown injected inside the measured stage
  wall, must flag EXACTLY ONE ``perf.regression`` naming that
  fingerprint and ``stage_wall_s`` as the most-moved component —
  with ``TFT_TRACE`` off — and surface it through ``tft.regressions()``,
  ``tft.why()``, ``tft.doctor()``, ``tft.health()`` warnings, and
  ``serve_report()``.

Sleep-based assertions are ``timing``-marked with ``timing_margin()``
per the tier-1 flake note.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from conftest import timing_margin
from tensorframes_tpu.memory import persist
from tensorframes_tpu.observability import baseline, flight, timeline
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.serve import QueryScheduler
from tensorframes_tpu.serve.stats import serve_report
from tensorframes_tpu.utils import tracing

pytestmark = pytest.mark.sentinel


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("TFT_TIMELINE", "TFT_TIMELINE_INTERVAL_S",
                "TFT_TIMELINE_SAMPLES", "TFT_BASELINE_SAMPLES",
                "TFT_BASELINE_MIN", "TFT_REGRESSION_SIGMA",
                "TFT_REGRESSION_MIN_FRAC", "TFT_REGRESSION_MIN_S",
                "TFT_FAULT_PERF_S", "TFT_FAULTS", "TFT_FLIGHT",
                "TFT_PERSIST_DIR"):
        monkeypatch.delenv(var, raising=False)
    tracing.disable()
    faults.reset()
    flight.clear()
    baseline.clear()
    timeline.clear()
    yield
    faults.reset()
    flight.clear()
    baseline.clear()
    timeline.clear()
    tracing.disable()


def _frame(n=256, offset=0.0):
    return tft.frame({"x": np.arange(float(n)) + offset},
                     num_partitions=4)


def _fused(n=256, offset=0.0):
    # two chained map_blocks so the forcing takes the FUSED plan path
    # (plan/execute._run) — where the perf fault site and the
    # stage-wall feedback hook both live
    return _frame(n, offset).map_blocks(lambda x: {"y": x * 2.0 + 1.0}) \
                            .map_blocks(lambda y: {"z": y - 3.0})


def _run_one(sched, frame, tenant="drill"):
    fut = sched.submit(frame, tenant=tenant)
    sched.step()
    return fut.result(timeout=timing_margin(30))


# ---------------------------------------------------------------------------
# timeline ring
# ---------------------------------------------------------------------------

class TestTimeline:
    def test_sample_now_and_query(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE_INTERVAL_S", "0")
        tracing.counters.inc("sentineltest.widgets", 5)
        assert timeline.sample_now()
        tracing.counters.inc("sentineltest.widgets", 7)
        assert timeline.sample_now()
        tl = tft.timeline("sentineltest.widgets")
        assert tl["samples"] >= 2
        # the delta between the two snapshots is exactly the increment
        assert tl["deltas"][-1]["delta"] == 7
        assert tl["total_delta"] >= 7
        assert "sentineltest.widgets" in timeline.families()

    def test_prefix_aggregation(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE_INTERVAL_S", "0")
        assert timeline.sample_now()
        tracing.counters.inc("sentineltest.a", 3)
        tracing.counters.inc("sentineltest.b", 4)
        assert timeline.sample_now()
        tl = tft.timeline("sentineltest")  # prefix sums a + b
        assert tl["deltas"][-1]["delta"] == 7

    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE_INTERVAL_S", "0")
        monkeypatch.setenv("TFT_TIMELINE_SAMPLES", "4")
        timeline.clear()
        for _ in range(10):
            assert timeline.sample_now()
        st = timeline.stats()
        assert st["samples"] == 4
        assert st["capacity"] == 4
        assert st["taken_total"] == 10
        assert st["dropped_total"] == 6

    def test_interval_gates_maybe_sample(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE_INTERVAL_S", "3600")
        timeline.clear()
        assert timeline.maybe_sample()
        for _ in range(5):
            assert not timeline.maybe_sample()  # inside the interval
        assert timeline.stats()["samples"] == 1

    def test_window_filter(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE_INTERVAL_S", "0")
        assert timeline.sample_now()
        assert timeline.sample_now()
        assert len(timeline.recent_samples()) >= 2
        assert timeline.recent_samples(window_s=0.0) == []


# ---------------------------------------------------------------------------
# TFT_TIMELINE=0: whole-sentinel bypass, bit-identical results
# ---------------------------------------------------------------------------

class TestBypass:
    def test_disabled_takes_no_samples(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE", "0")
        assert not timeline.enabled()
        assert not baseline.enabled()
        assert not timeline.sample_now()
        assert not timeline.maybe_sample()
        assert timeline.stats()["samples"] == 0
        assert tft.timeline("anything")["samples"] == 0

    def test_disabled_capture_is_a_noop(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE", "0")
        with baseline.capture("bypass-q", tenant="t"):
            baseline.note_stage_wall(1.0)
            baseline.note_wait(1.0)
            assert baseline.slow_context() is None
            assert baseline.finalize(latency_s=9.9) is None
        assert baseline.perf_stats()["baselines"] == 0
        assert baseline.perf_stats()["completions_total"] == 0

    def test_disabled_results_bit_identical(self, monkeypatch):
        with QueryScheduler(workers=0, name="byp-on") as s:
            on = _run_one(s, _fused()).blocks()
        monkeypatch.setenv("TFT_TIMELINE", "0")
        with QueryScheduler(workers=0, name="byp-off") as s:
            off = _run_one(s, _fused()).blocks()
        assert len(on) == len(off)
        for a, b in zip(on, off):
            for name in a.columns:
                np.testing.assert_array_equal(
                    np.asarray(a.columns[name]),
                    np.asarray(b.columns[name]))


# ---------------------------------------------------------------------------
# cost attribution + baselines
# ---------------------------------------------------------------------------

class TestBaselines:
    def test_completion_builds_a_baseline(self, monkeypatch):
        monkeypatch.setenv("TFT_BASELINE_MIN", "2")
        with QueryScheduler(workers=0, name="bl") as s:
            for _ in range(3):
                _run_one(s, _fused())
        st = baseline.perf_stats()
        assert st["baselines"] == 1  # same logical plan every time
        assert st["warm_baselines"] == 1
        assert st["completions_total"] == 3

    def test_vector_components_present(self):
        with QueryScheduler(workers=0, name="vec") as s:
            _run_one(s, _fused())
        (bl,) = baseline._baselines.values()
        for comp in baseline.COMPONENTS:
            assert comp in bl.window, comp
        # the fused forcing's stage wall was attributed
        assert bl.window["stage_wall_s"][-1] > 0.0
        assert bl.window["latency_s"][-1] > 0.0

    def test_failed_runs_do_not_calibrate(self):
        with QueryScheduler(workers=0, name="fail") as s:
            faults.arm("dispatch", 100, transient=False)
            fut = s.submit(_fused(), tenant="t")
            s.step()
            with pytest.raises(Exception):
                fut.result(timeout=timing_margin(30))
        assert baseline.perf_stats()["baselines"] == 0

    def test_fingerprint_stable_across_resubmission(self):
        from tensorframes_tpu.plan.adaptive import query_fingerprint
        fp1 = query_fingerprint(_fused())
        fp2 = query_fingerprint(_fused())
        assert fp1 is not None and fp1 == fp2
        # a different chain gets a different fingerprint
        fp3 = query_fingerprint(
            _frame().map_blocks(lambda x: {"w": x * x})
                    .map_blocks(lambda w: {"v": w + 1.0}))
        assert fp3 is not None and fp3 != fp1

    def test_portable_baseline_persists(self, tmp_path):
        prev = persist.configure(str(tmp_path))
        try:
            bl = baseline.Baseline("f" * 64, portable=True)
            bl.update({c: 1.0 for c in baseline.COMPONENTS})
            baseline._save_persisted(bl)
            assert persist.stats()["baselines"] == 1
            loaded = baseline.Baseline.from_payload(
                persist.load_baseline("f" * 64))
            assert loaded is not None
            assert loaded.count == 1
            assert list(loaded.window["latency_s"]) == [1.0]
            # process-local fingerprints never touch disk
            local = baseline.Baseline("e" * 64, portable=False)
            local.update({c: 1.0 for c in baseline.COMPONENTS})
            baseline._save_persisted(local)
            assert persist.stats()["baselines"] == 1
        finally:
            persist.configure(prev)

    def test_regression_math_guards(self):
        bl = baseline.Baseline("a" * 64, portable=False)
        for _ in range(8):
            bl.update({c: (1.0 if c == "latency_s" else 0.0)
                       for c in baseline.COMPONENTS})
        z, med = bl.deviation("latency_s", 1.0)
        assert med == 1.0 and z == 0.0
        # far beyond any MAD floor: sigma is huge
        z, _ = bl.deviation("latency_s", 5.0)
        assert z > 100


# ---------------------------------------------------------------------------
# the scripted regression drill
# ---------------------------------------------------------------------------

@pytest.mark.timing
class TestRegressionDrill:
    def test_drill_flags_exactly_one_regression(self, monkeypatch):
        # K warm runs, then one injected slowdown INSIDE the measured
        # stage wall — TFT_TRACE stays off the whole way (the sentinel
        # must not depend on tracing)
        monkeypatch.setenv("TFT_BASELINE_MIN", "3")
        slow_s = timing_margin(0.5)
        monkeypatch.setenv("TFT_FAULT_PERF_S", str(slow_s))
        with QueryScheduler(workers=0, name="drill") as s:
            for _ in range(6):
                out = _run_one(s, _fused())
            from tensorframes_tpu.plan.adaptive import query_fingerprint
            expected_fp = query_fingerprint(_fused())[0]
            assert baseline.perf_stats()["warm_baselines"] == 1
            faults.arm("perf", 1)
            _run_one(s, _fused())
            regs = tft.regressions()
            assert len(regs) == 1, regs
            reg = regs[0]
            assert reg["fingerprint"] == expected_fp
            assert reg["component"] == "stage_wall_s"
            assert reg["observed"] >= slow_s
            assert reg["latency_s"] > reg["baseline_latency_s"]
            assert reg["tenant"] == "drill"
            # one flight anomaly, input-leading in tft.why()
            recs = flight.recent("perf.regression")
            assert len(recs) == 1
            assert recs[0]["query"] == reg["query"]
            assert recs[0]["component"] == "stage_wall_s"
            why = tft.why(reg["query"])
            assert "PERF REGRESSION" in why
            assert "stage_wall_s" in why
            # health warning names the most-moved component
            warns = [w for w in tft.health()["warnings"]
                     if w.startswith("perf:")]
            assert len(warns) == 1
            assert "stage_wall_s" in warns[0]
            # serve_report per-tenant row
            report = serve_report(s)
            assert "PERF: 1 regression(s)" in report
            assert expected_fp[:16] in report
            # a healthy follow-up run (same warm scheduler: no fresh
            # compile to pay) does NOT flag again — the rolling window
            # is MAD-robust to the one slow outlier it absorbed
            _run_one(s, _fused())
            assert len(tft.regressions()) == 1
        # doctor groups by fingerprint
        doc = tft.doctor()
        assert "perf regressions by plan fingerprint" in doc
        assert expected_fp[:16] in doc

    def test_drill_quiet_when_disabled(self, monkeypatch):
        monkeypatch.setenv("TFT_TIMELINE", "0")
        monkeypatch.setenv("TFT_BASELINE_MIN", "3")
        monkeypatch.setenv("TFT_FAULT_PERF_S",
                           str(timing_margin(0.3)))
        with QueryScheduler(workers=0, name="quiet") as s:
            for _ in range(4):
                _run_one(s, _fused())
            faults.arm("perf", 1)
            _run_one(s, _fused())
        assert tft.regressions() == []
        assert flight.recent("perf.regression") == []


# ---------------------------------------------------------------------------
# slow-query enrichment + metrics
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_slow_context_carries_cost_vector(self):
        with baseline.capture("slowq", tenant="t"):
            baseline.note_stage_wall(0.25)
            ctx = baseline.slow_context()
        assert ctx is not None
        assert ctx["cost"]["stage_wall_s"] == 0.25
        for comp in baseline.COMPONENTS:
            assert comp in ctx["cost"]

    def test_metrics_providers_render(self):
        from tensorframes_tpu.observability import metrics
        providers = metrics.registered_providers()
        assert "perf" in providers
        assert "timeline" in providers
        text = metrics.metrics_text()
        assert "tft_perf_baselines" in text
        assert "tft_perf_regressions_total" in text
        assert "tft_timeline_samples_total" in text

    def test_perf_stats_shape(self):
        st = baseline.perf_stats()
        for key in ("enabled", "baselines", "warm_baselines",
                    "completions_total", "regressions_total",
                    "recent_regressions", "timeline"):
            assert key in st
