"""Elastic meshes: device-loss tolerance, lost-shard re-execution, and
skew-adaptive repartitioning (``tensorframes_tpu/parallel/elastic.py``).

The acceptance spine: with the deterministic ``device`` fault site armed
on the 8-virtual-device CPU mesh, every mesh op completes with results
bit-identical to the healthy-mesh run (integer columns pin bit-identity
— float reductions may reassociate across shard counts, like any
resharding), ``mesh.devices_lost`` counts the loss, and a ``mesh_shrink``
event carrying the lost device id lands in the query trace. The skew
half: synthetic per-device timings fed through the tracker trigger a
proportional re-partition, and ``daggregate`` salts hot keys.
"""

import time

import jax
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import parallel as par
from tensorframes_tpu import resilience as rz
from tensorframes_tpu.observability import events as obs_events
from tensorframes_tpu.parallel import elastic
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils import tracing
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.elastic


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return par.local_mesh(8)


@pytest.fixture(autouse=True)
def _clean():
    counters.reset()
    faults.reset()
    elastic._tracker.clear()
    elastic._upgrades.clear()
    elastic._lost_pool.clear()
    yield
    faults.reset()
    elastic._tracker.clear()
    elastic._upgrades.clear()
    elastic._lost_pool.clear()
    tracing.disable()


def _int_frame(n=40, keys=5):
    return tft.frame({"k": np.arange(n) % keys,
                      "x": np.arange(n)})


# ---------------------------------------------------------------------------
# classification + fault site
# ---------------------------------------------------------------------------

class TestClassification:
    def test_device_lost_markers(self):
        e = RuntimeError("DEVICE_LOST: device 2 halted")
        assert rz.is_device_lost(e)
        assert rz.error_kind(e) == "device_lost"
        assert not rz.is_transient(e)

    def test_device_lost_beats_transient_markers(self):
        # "UNAVAILABLE: device lost" must shrink the mesh, not spin the
        # retry loop against a dead chip
        e = RuntimeError("UNAVAILABLE: device lost during collective")
        assert rz.error_kind(e) == "device_lost"
        assert not rz.is_transient(e)

    def test_device_lost_exception_class(self):
        assert rz.error_kind(rz.DeviceLost("chip 3 gone")) == "device_lost"

    def test_device_fault_site_default_shape(self):
        faults.arm("device", 1)
        with pytest.raises(faults.InjectedFault) as ei:
            faults.check("device")
        assert rz.error_kind(ei.value) == "device_lost"
        assert "device 0" in str(ei.value)

    def test_device_fault_site_env_device(self, monkeypatch):
        monkeypatch.setenv("TFT_FAULT_DEVICE", "5")
        faults.arm("device", 1)
        with pytest.raises(faults.InjectedFault) as ei:
            faults.check("device")
        assert "device 5" in str(ei.value)

    def test_tft_faults_env_arms_device_site(self, monkeypatch):
        # the acceptance drive: TFT_FAULTS=device:1 arms the site at
        # first check with the DEVICE_LOST-shaped default message
        monkeypatch.setenv("TFT_FAULTS", "device:1")
        monkeypatch.setattr(faults._state, "_armed_env", False)
        assert faults.active("device") == 1
        with pytest.raises(faults.InjectedFault) as ei:
            faults.check("device")
        assert rz.error_kind(ei.value) == "device_lost"

    def test_lost_device_ids_parsed_from_message(self, mesh8):
        e = RuntimeError("DEVICE_LOST: device 6 is lost")
        assert elastic.lost_device_ids(e, mesh8) == [6]

    def test_lost_device_ids_defaults_to_zero(self, mesh8):
        # anonymous loss on a healthy host-backed mesh: documented
        # deterministic fallback
        assert elastic.lost_device_ids(
            RuntimeError("DEVICE_LOST"), mesh8) == [0]


# ---------------------------------------------------------------------------
# device-loss recovery (the acceptance criterion)
# ---------------------------------------------------------------------------

class TestDeviceLossRecovery:
    def _assert_shrink_trace(self, lost_device=0):
        t = obs_events.last_query()
        shr = [ev for ev in t.events if ev.etype == "mesh_shrink"]
        assert len(shr) == 1
        assert shr[0].args["device"] == lost_device
        assert shr[0].args["devices_before"] == 8
        assert shr[0].args["devices_after"] == 7
        assert t.summary()["mesh_shrinks"] == 1

    def test_dmap_blocks_bit_identical_after_loss(self, mesh8):
        dist = par.distribute(_int_frame(), mesh8)
        healthy = [r["z"] for r in par.dmap_blocks(
            lambda x: {"z": x * 2}, dist).collect_frame().collect()]
        tracing.enable()
        try:
            with faults.inject("device", 1):
                out = par.dmap_blocks(lambda x: {"z": x * 2}, dist)
        finally:
            tracing.disable()
        got = [r["z"] for r in out.collect_frame().collect()]
        assert got == healthy
        assert out.mesh.num_devices == 7
        assert counters.get("mesh.devices_lost") == 1
        assert counters.get("mesh.reshard_rows") > 0
        self._assert_shrink_trace()

    def test_daggregate_bit_identical_after_loss(self, mesh8):
        dist = par.distribute(_int_frame(), mesh8)
        healthy = par.daggregate({"x": "sum"}, dist, "k").collect()
        tracing.enable()
        try:
            with faults.inject("device", 1):
                out = par.daggregate({"x": "sum"}, dist, "k")
        finally:
            tracing.disable()
        assert out.collect() == healthy
        assert counters.get("mesh.devices_lost") == 1
        self._assert_shrink_trace()

    def test_dsort_bit_identical_after_loss(self, mesh8):
        rng = np.random.default_rng(7)
        df = tft.frame({"x": rng.permutation(40)})
        dist = par.distribute(df, mesh8)
        healthy = [r["x"] for r in par.dsort(
            "x", dist, descending=True).collect_frame().collect()]
        tracing.enable()
        try:
            with faults.inject("device", 1):
                out = par.dsort("x", dist, descending=True)
        finally:
            tracing.disable()
        got = [r["x"] for r in out.collect_frame().collect()]
        assert got == healthy
        assert counters.get("mesh.devices_lost") == 1
        self._assert_shrink_trace()

    def test_dfilter_and_dreduce_recover(self, mesh8):
        dist = par.distribute(_int_frame(), mesh8)
        with faults.inject("device", 1):
            flt = par.dfilter(lambda x: x % 2 == 0, dist)
        assert flt.count() == 20
        assert [r["x"] for r in flt.collect_frame().collect()] == \
            list(range(0, 40, 2))
        with faults.inject("device", 1):
            red = par.dreduce_blocks({"x": "sum"}, dist)
        assert int(red["x"]) == sum(range(40))
        assert counters.get("mesh.devices_lost") == 2

    def test_named_device_is_the_one_dropped(self, mesh8):
        dist = par.distribute(_int_frame(), mesh8)
        with faults.inject(
                "device", 1,
                message="DEVICE_LOST: injected: device 3 is lost"):
            out = par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        ids = [d.id for d in out.mesh.mesh.devices.flat]
        assert 3 not in ids and len(ids) == 7

    def test_two_successive_losses(self, mesh8):
        dist = par.distribute(_int_frame(80), mesh8)
        with faults.inject("device", 2):
            out = par.dmap_blocks(lambda x: {"z": x * 3}, dist)
        assert out.mesh.num_devices == 6
        assert counters.get("mesh.devices_lost") == 2
        assert counters.get("mesh.shrinks") == 2
        assert [r["z"] for r in out.collect_frame().collect()] == \
            [i * 3 for i in range(80)]

    def test_loss_on_filtered_frame_keeps_shard_valid_rows(self, mesh8):
        # the lost-shard re-shard must honor per-shard validity (the
        # dfilter layout), not just prefix frames
        dist = par.distribute(_int_frame(), mesh8)
        flt = par.dfilter(lambda x: x % 2 == 0, dist)
        assert flt.shard_valid is not None
        with faults.inject("device", 1):
            out = par.dmap_blocks(lambda x: {"z": x + 100}, flt)
        assert [r["z"] for r in out.collect_frame().collect()] == \
            [i + 100 for i in range(0, 40, 2)]

    def test_host_string_columns_survive_reshard(self, mesh8):
        df = tft.frame({"s": np.array(list("abcdefghij"), object),
                        "x": np.arange(10)})
        dist = par.distribute(df, mesh8)
        with faults.inject("device", 1):
            out = par.dmap_blocks(lambda x: {"z": x * 2}, dist)
        rows = out.collect_frame().collect()
        assert [r["s"] for r in rows] == list("abcdefghij")
        assert [r["z"] for r in rows] == [i * 2 for i in range(10)]

    def test_elastic_disabled_raises(self, mesh8, monkeypatch):
        monkeypatch.setenv("TFT_ELASTIC", "0")
        dist = par.distribute(_int_frame(), mesh8)
        with faults.inject("device", 1):
            with pytest.raises(faults.InjectedFault):
                par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)
        assert counters.get("mesh.devices_lost") == 0

    def test_single_shard_mesh_reraises(self):
        mesh1 = par.local_mesh(1)
        dist = par.distribute(tft.frame({"x": np.arange(4)}), mesh1)
        with faults.inject("device", 1):
            with pytest.raises(faults.InjectedFault):
                par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)

    def test_mesh_metrics_series_exported(self, mesh8):
        from tensorframes_tpu.observability.metrics import metrics_text

        dist = par.distribute(_int_frame(), mesh8)
        with faults.inject("device", 1):
            par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        text = metrics_text()
        assert "tft_mesh_devices_lost_total 1" in text
        assert "tft_mesh_shrinks_total 1" in text
        assert "tft_mesh_reshard_rows_total" in text

    def test_report_renders_shrink(self, mesh8):
        dist = par.distribute(_int_frame(), mesh8)
        tracing.enable()
        try:
            with faults.inject("device", 1):
                par.daggregate({"x": "sum"}, dist, "k")
            rep = tft.last_query_report()
        finally:
            tracing.disable()
        assert "mesh shrunk 8 -> 7" in rep


# ---------------------------------------------------------------------------
# skew-adaptive repartitioning
# ---------------------------------------------------------------------------

class TestSkewRebalance:
    SKEWED = [0.001] * 7 + [0.01]

    def test_persistent_skew_repartitions_proportionally(self, mesh8):
        dist = par.distribute(tft.frame({"x": np.arange(80)}), mesh8)
        for _ in range(3):
            elastic.note_dispatch(mesh8, "dmap_blocks", self.SKEWED)
        out = par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        rb = getattr(out, "_rebalance", None)
        assert rb is not None
        assert counters.get("mesh.rebalances") == 1
        # the slow device ends up with the fewest rows; totals conserved
        assert sum(rb["after"]) == 80
        assert rb["after"][-1] == min(rb["after"])
        assert rb["after"][-1] < min(rb["before"])
        # rows and order are untouched by the re-partition
        assert [r["z"] for r in out.collect_frame().collect()] == \
            [i + 1 for i in range(80)]
        assert "rebalance" in out.explain()

    def test_rebalance_acts_once_per_streak(self, mesh8):
        dist = par.distribute(tft.frame({"x": np.arange(80)}), mesh8)
        for _ in range(3):
            elastic.note_dispatch(mesh8, "op", self.SKEWED)
        par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)
        out2 = par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)
        assert getattr(out2, "_rebalance", None) is None
        assert counters.get("mesh.rebalances") == 1

    def test_balanced_dispatch_resets_streak(self, mesh8):
        dist = par.distribute(tft.frame({"x": np.arange(80)}), mesh8)
        for _ in range(2):
            elastic.note_dispatch(mesh8, "op", self.SKEWED)
        elastic.note_dispatch(mesh8, "op", [0.001] * 8)  # balanced
        elastic.note_dispatch(mesh8, "op", self.SKEWED)
        out = par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)
        assert getattr(out, "_rebalance", None) is None
        assert counters.get("mesh.rebalances") == 0

    def test_rebalance_disabled_by_env(self, mesh8, monkeypatch):
        monkeypatch.setenv("TFT_SKEW_REBALANCE_AFTER", "0")
        for _ in range(5):
            elastic.note_dispatch(mesh8, "op", self.SKEWED)
        dist = par.distribute(tft.frame({"x": np.arange(80)}), mesh8)
        out = par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)
        assert getattr(out, "_rebalance", None) is None

    def test_rebalance_event_in_trace(self, mesh8):
        dist = par.distribute(tft.frame({"x": np.arange(80)}), mesh8)
        for _ in range(3):
            elastic.note_dispatch(mesh8, "dmap_blocks", self.SKEWED)
        tracing.enable()
        try:
            par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        finally:
            tracing.disable()
        t = obs_events.last_query()
        evs = [ev for ev in t.events if ev.etype == "rebalance"]
        assert len(evs) == 1
        assert sum(evs[0].args["after"]) == 80
        assert t.summary()["rebalances"] == 1


# ---------------------------------------------------------------------------
# hot-key salting
# ---------------------------------------------------------------------------

class TestHotKeySalting:
    def _hot_frame(self, n=8_000):
        keys = np.zeros(n, np.int64)
        keys[: n // 5] = np.arange(n // 5) % 7 + 1  # key 0 holds 80%
        return tft.frame({"k": keys, "v": np.arange(n)})

    def test_hot_key_salted_and_exact(self, mesh8):
        df = self._hot_frame()
        dist = par.distribute(df, mesh8)
        host = {r["k"]: r["v"] for r in
                tft.aggregate({"v": "sum"}, df.group_by("k")).collect()}
        out = {r["k"]: r["v"] for r in
               par.daggregate({"v": "sum"}, dist, "k").collect()}
        assert counters.get("mesh.salted_keys") == 1
        assert out == host  # integer sums: exact under any association

    def test_salting_cached_per_frame(self, mesh8):
        df = self._hot_frame()
        dist = par.distribute(df, mesh8)
        a1 = par.daggregate({"v": "sum"}, dist, "k").collect()
        a2 = par.daggregate({"v": "sum"}, dist, "k").collect()
        assert a1 == a2
        assert counters.get("mesh.salted_keys") == 1  # planned once

    def test_min_max_fold_back_exact(self, mesh8):
        df = self._hot_frame()
        dist = par.distribute(df, mesh8)
        host = {r["k"]: r["v"] for r in
                tft.aggregate({"v": "min"}, df.group_by("k")).collect()}
        out = {r["k"]: r["v"] for r in
               par.daggregate({"v": "min"}, dist, "k").collect()}
        assert out == host

    def test_no_hot_key_no_salting(self, mesh8):
        n = 8_000
        df = tft.frame({"k": np.arange(n) % 16, "v": np.arange(n)})
        dist = par.distribute(df, mesh8)
        par.daggregate({"v": "sum"}, dist, "k")
        assert counters.get("mesh.salted_keys") == 0

    def test_salting_disabled_by_env(self, mesh8, monkeypatch):
        monkeypatch.setenv("TFT_SALT_HOT_KEYS", "0")
        df = self._hot_frame()
        dist = par.distribute(df, mesh8)
        host = {r["k"]: r["v"] for r in
                tft.aggregate({"v": "sum"}, df.group_by("k")).collect()}
        out = {r["k"]: r["v"] for r in
               par.daggregate({"v": "sum"}, dist, "k").collect()}
        assert counters.get("mesh.salted_keys") == 0
        assert out == host


# ---------------------------------------------------------------------------
# local_mesh validation (satellite)
# ---------------------------------------------------------------------------

class TestLocalMeshValidation:
    def test_shape_validated_against_num_devices(self):
        with pytest.raises(ValueError, match=r"num_devices=4"):
            par.local_mesh(4, shape=(8,))

    def test_more_than_visible_raises_clearly(self):
        with pytest.raises(ValueError, match=r"num_devices=16.*8 visible"):
            par.local_mesh(16)

    def test_shape_without_num_devices_names_visible(self):
        with pytest.raises(ValueError, match=r"3 device\(s\) but 8"):
            par.local_mesh(shape=(3,))

    def test_valid_combinations_still_work(self):
        assert par.local_mesh(4, shape=(4,)).num_devices == 4
        assert par.local_mesh(8).num_devices == 8


# ---------------------------------------------------------------------------
# device loss during streaming and serving (satellite)
# ---------------------------------------------------------------------------

class TestStreamAndServeRideTheElasticPath:
    def test_stream_keeps_folding_through_device_loss(self, mesh8):
        """A background pump keeps folding while a mesh query loses a
        device: zero rows lost or duplicated on either side."""
        from tensorframes_tpu import stream as tstream

        n_batches, rows = 12, 64

        def gen():
            for i in range(n_batches):
                yield {"k": np.arange(rows) % 4,
                       "v": np.full(rows, i, np.int64)}

        agg = (tstream.from_source(tstream.GeneratorSource(gen()))
               .group_by("k").aggregate({"v": "sum"}))
        handle = agg.start(name="elastic-stream").start_background(
            poll_interval=0.001)
        # mid-stream: a distributed query loses a device and recovers
        dist = par.distribute(_int_frame(80), mesh8)
        with faults.inject("device", 1):
            out = par.dmap_blocks(lambda x: {"z": x * 2}, dist)
        assert out.mesh.num_devices == 7
        deadline = time.monotonic() + 30
        while not handle.done() and time.monotonic() < deadline:
            time.sleep(0.01)
        handle.stop()
        m = handle.metrics()
        assert m["batches"] == n_batches
        assert m["batches_skipped"] == 0
        assert m["rows"] == n_batches * rows
        # exact fold: sum of v per key over every batch, nothing lost
        # or double-counted across the concurrent recovery (update-mode
        # deltas are cumulative; the finalize snapshot lands last, so
        # the last value seen per key is the total)
        got = {r["k"]: r["v"] for fr in handle.collect_updates()
               for r in fr.collect()}
        per_key = sum(range(n_batches)) * (rows // 4)
        assert got == {k: per_key for k in range(4)}
        assert counters.get("mesh.devices_lost") == 1

    def test_stream_batch_device_lost_retried_once(self):
        """A device-lost error escaping into the batch path is retried
        once (the mesh below has shrunk), not counted as poisoned."""
        from tensorframes_tpu import stream as tstream

        def gen():
            for i in range(3):
                yield {"v": np.arange(4.0) + i}

        sf = tstream.from_source(tstream.GeneratorSource(gen()))
        handle = sf.start(name="dl-retry")
        with faults.inject("batch", 1,
                           message="DEVICE_LOST: device 1 is lost",
                           transient=False):
            n = handle.run()
        assert n == 3
        m = handle.metrics()
        assert m["batches"] == 3
        assert m["batches_skipped"] == 0
        assert counters.get("stream.device_lost_retries") == 1

    def test_serve_mix_completes_through_device_loss(self, mesh8):
        """Multi-tenant submit() mix in flight while a mesh query loses
        a device: every future completes, the mesh query finishes on
        the shrunken mesh, and results are exact."""
        from tensorframes_tpu.serve import QueryScheduler, TenantQuota

        quotas = {"a": TenantQuota(weight=1.0),
                  "b": TenantQuota(weight=2.0)}
        with QueryScheduler(quotas=quotas, workers=2,
                            name="elastic-serve") as sched:
            futs = []
            for i in range(6):
                fr = tft.frame({"x": np.arange(32.0) + i})
                futs.append((i, sched.submit(
                    fr, lambda x: {"z": x + 1.0},
                    tenant="a" if i % 2 else "b")))
            dist = par.distribute(_int_frame(80), mesh8)
            with faults.inject("device", 1):
                out = par.dmap_blocks(lambda x: {"z": x * 2}, dist)
            assert out.mesh.num_devices == 7
            assert [r["z"] for r in out.collect_frame().collect()] == \
                [i * 2 for i in range(80)]
            for i, fut in futs:
                res = fut.result(timeout=30)
                got = [r["z"] for b in [res] for r in b.collect()]
                assert got == list(np.arange(32.0) + i + 1.0)
        assert counters.get("mesh.devices_lost") == 1

    def test_serve_thunk_device_lost_retried_once(self):
        """A device-lost error raised by a served query's own forcing is
        retried once instead of failing the future."""
        from tensorframes_tpu.serve import QueryScheduler

        with QueryScheduler(workers=0, name="dl-serve") as sched:
            fr = tft.frame({"x": np.arange(8.0)})
            fut = sched.submit(fr, lambda x: {"z": x + 1.0}, tenant="t")
            with faults.inject("dispatch", 1,
                               message="DEVICE_LOST: device 0 is lost",
                               transient=False):
                assert sched.step()
            res = fut.result(timeout=30)
            assert [r["z"] for r in res.collect()] == \
                list(np.arange(8.0) + 1.0)
        assert counters.get("serve.device_lost_retries") == 1


# ---------------------------------------------------------------------------
# reshard invariants
# ---------------------------------------------------------------------------

class TestReshard:
    def test_prefix_reshard_preserves_order(self, mesh8):
        dist = par.distribute(_int_frame(20), mesh8)
        small = elastic.shrink_mesh(dist.mesh, [2])
        out = elastic.reshard(dist, small)
        assert out.num_rows == 20
        assert out.mesh.num_data_shards == 7
        assert [r["x"] for r in out.collect_frame().collect()] == \
            list(range(20))

    def test_explicit_shard_rows_layout(self, mesh8):
        dist = par.distribute(_int_frame(16), mesh8)
        rows = np.array([4, 4, 2, 2, 2, 1, 1, 0])
        out = elastic.reshard(dist, dist.mesh, shard_rows=rows)
        assert list(out.per_shard_valid()) == list(rows)
        assert [r["x"] for r in out.collect_frame().collect()] == \
            list(range(16))

    def test_bad_shard_rows_rejected(self, mesh8):
        dist = par.distribute(_int_frame(16), mesh8)
        with pytest.raises(ValueError, match="does not distribute"):
            elastic.reshard(dist, dist.mesh,
                            shard_rows=np.array([1] * 8))

    def test_shrink_rejects_non_data_mesh(self):
        mesh = par.local_mesh(8, axis_names=("data", "model"),
                              shape=(4, 2))
        with pytest.raises(ValueError, match="data-only"):
            elastic.shrink_mesh(mesh, [0])

    def test_shrink_keeps_non_leading_data_axis(self):
        # survivors must land on the DATA axis wherever it sits, not
        # on axis 0
        from jax.sharding import Mesh

        devices = np.array(jax.devices()).reshape(1, 8)
        mesh = par.DeviceMesh(Mesh(devices, ("model", "data")),
                              data_axis="data")
        small = elastic.shrink_mesh(mesh, [2])
        assert dict(small.mesh.shape) == {"model": 1, "data": 7}
        assert small.num_data_shards == 7

    def test_loss_after_rebalance_drops_stale_record(self, mesh8):
        # a loss inside the same call re-shards with an even prefix
        # layout; the pre-loss rebalance info must not be reported
        dist = par.distribute(tft.frame({"x": np.arange(80)}), mesh8)
        for _ in range(3):
            elastic.note_dispatch(mesh8, "dmap_blocks",
                                  [0.001] * 7 + [0.01])
        with faults.inject("device", 1):
            out = par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        assert out.mesh.num_devices == 7
        assert getattr(out, "_rebalance", None) is None
        assert [r["z"] for r in out.collect_frame().collect()] == \
            [i + 1 for i in range(80)]


# ---------------------------------------------------------------------------
# elastic mesh GROWTH: probe + admit + migrate + churn (the PR 13 half;
# also in the --preempt lane)
# ---------------------------------------------------------------------------

@pytest.mark.preempt
class TestElasticGrowth:
    def test_admit_devices_grows_and_stays_bit_identical(self):
        mesh6 = par.local_mesh(6)
        dist = par.distribute(_int_frame(), mesh6)
        healthy = [r["z"] for r in par.dmap_blocks(
            lambda x: {"z": x * 2}, dist).collect_frame().collect()]
        tracing.enable()
        try:
            grown = par.admit_devices(dist)
        finally:
            tracing.disable()
        assert grown.mesh.num_devices == 8
        got = [r["z"] for r in par.dmap_blocks(
            lambda x: {"z": x * 2}, grown).collect_frame().collect()]
        assert got == healthy
        assert counters.get("mesh.grows") == 1
        assert counters.get("mesh.devices_admitted") == 2

    def test_grow_mesh_is_inverse_of_shrink(self, mesh8):
        small = elastic.shrink_mesh(mesh8, [3])
        lost = mesh8.mesh.devices.flat[3]
        back = elastic.grow_mesh(small, [lost])
        assert back.num_devices == 8
        assert lost in list(back.mesh.devices.flat)
        # idempotent: already-member devices are ignored
        assert elastic.grow_mesh(back, [lost]) is back

    def test_other_frames_migrate_at_next_dispatch(self):
        mesh6 = par.local_mesh(6)
        a = par.distribute(_int_frame(), mesh6)
        b = par.distribute(_int_frame(), mesh6)  # same mesh, untouched
        par.admit_devices(a)
        out = par.dmap_blocks(lambda x: {"z": x + 1}, b)
        assert out.mesh.num_devices == 8
        assert counters.get("mesh.grow_migrations") == 1
        assert [r["z"] for r in out.collect_frame().collect()] == \
            [i + 1 for i in range(40)]

    def test_fresh_user_mesh_not_captured_by_old_upgrade(self):
        # the upgrade registry is keyed by mesh OBJECT identity: a
        # fresh mesh a user later builds over the same devices
        # (deliberately excluding the admitted ones) must keep its
        # layout
        mesh6 = par.local_mesh(6)
        a = par.distribute(_int_frame(), mesh6)
        par.admit_devices(a)
        fresh6 = par.local_mesh(6)
        b = par.distribute(_int_frame(), fresh6)
        out = par.dmap_blocks(lambda x: {"z": x + 1}, b)
        assert out.mesh.num_devices == 6  # not migrated
        assert counters.get("mesh.grow_migrations") == 0

    def test_default_candidates_prefer_lost_devices(self):
        # with a genuinely lost device in the pool, the default
        # candidate set is exactly the recovered chips — another live
        # mesh's healthy devices (6, 7 here) are not absorbed
        mesh6 = par.local_mesh(6)
        dist = par.distribute(_int_frame(), mesh6)
        with faults.inject(
                "device", 1,
                message="DEVICE_LOST: injected: device 2 is lost"):
            out = par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        assert out.mesh.num_devices == 5
        regrown = par.admit_devices(
            par.distribute(_int_frame(), out.mesh))
        assert regrown.mesh.num_devices == 6
        ids = sorted(int(d.id) for d in regrown.mesh.mesh.devices.flat)
        assert ids == [0, 1, 2, 3, 4, 5]
        assert counters.get("mesh.devices_admitted") == 1

    def test_admit_on_mesh_returns_grown_mesh(self):
        mesh6 = par.local_mesh(6)
        grown = par.admit_devices(mesh6)
        assert isinstance(grown, par.DeviceMesh)
        assert grown.num_devices == 8

    def test_no_candidates_is_a_no_op(self, mesh8):
        dist = par.distribute(_int_frame(), mesh8)
        assert par.admit_devices(dist) is dist
        assert counters.get("mesh.grows") == 0

    def test_failed_probe_is_not_admitted(self):
        mesh6 = par.local_mesh(6)

        class DeadChip:
            id = 99

            def __repr__(self):
                return "DeadChip(99)"

        assert elastic.probe_device(DeadChip()) is False
        grown = par.admit_devices(mesh6, devices=[DeadChip()])
        assert grown is mesh6  # unchanged: nothing passed the probe
        assert counters.get("mesh.admit_probe_failures") == 1
        assert counters.get("mesh.grows") == 0

    @pytest.mark.timing
    def test_admit_probe_timeout_bounded(self, monkeypatch):
        from conftest import timing_margin
        real_put = jax.device_put

        def hung_put(x, device=None, **kw):
            time.sleep(1.5)
            return real_put(x)

        monkeypatch.setattr(jax, "device_put", hung_put)
        t0 = time.monotonic()
        ok = elastic.probe_device(jax.devices()[0], timeout_s=0.2)
        elapsed = time.monotonic() - t0
        assert ok is False
        assert elapsed <= timing_margin(5.0), \
            f"probe timeout took {elapsed:.2f}s"

    def test_admit_clears_stale_skew_penalties(self):
        mesh6 = par.local_mesh(6)
        mesh8_full = par.local_mesh(8)
        # penalties recorded against BOTH the shrunken layout and the
        # full layout the devices are returning to must clear
        for _ in range(3):
            elastic.note_dispatch(mesh6, "dmap_blocks",
                                  [0.001] * 5 + [0.01])
            elastic.note_dispatch(mesh8_full, "dmap_blocks",
                                  [0.001] * 7 + [0.01])
        assert elastic._tracker
        par.admit_devices(mesh6)
        assert elastic._mesh_key(mesh6) not in elastic._tracker
        assert elastic._mesh_key(mesh8_full) not in elastic._tracker

    def test_shrink_forgets_upgrades_onto_lost_devices(self, mesh8):
        # grow registered mesh6 -> mesh8; a loss of a re-admitted
        # device must drop that upgrade or the next op would migrate
        # straight back onto the dead chip
        mesh6 = par.local_mesh(6)
        par.admit_devices(mesh6)
        assert elastic._upgrades
        dist = par.distribute(_int_frame(), mesh8)
        with faults.inject(
                "device", 1,
                message="DEVICE_LOST: injected: device 6 is lost"):
            par.dmap_blocks(lambda x: {"z": x + 1}, dist)
        assert not elastic._upgrades

    def test_grow_event_in_trace_and_report(self):
        mesh6 = par.local_mesh(6)
        dist = par.distribute(_int_frame(), mesh6)
        tracing.enable()
        try:
            with obs_events.query_trace("test_grow"):
                grown = par.admit_devices(dist)
            t = obs_events.last_query()
        finally:
            tracing.disable()
        assert grown.mesh.num_devices == 8
        grows = [ev for ev in t.events if ev.etype == "mesh_grow"]
        assert len(grows) == 1
        assert grows[0].args["devices_before"] == 6
        assert grows[0].args["devices_after"] == 8
        assert t.summary()["mesh_grows"] == 1
        assert "re-admitted" in t.report()

    def test_churn_shrink_grow_shrink_zero_lost_rows(self, mesh8):
        # the acceptance loop: the full d-op suite through a
        # shrink -> grow -> shrink churn, integer results bit-identical
        # to the healthy mesh, zero lost or duplicated rows
        df = _int_frame(80)
        healthy = par.distribute(df, mesh8)
        h_map = [r["z"] for r in par.dmap_blocks(
            lambda x: {"z": x * 2}, healthy).collect_frame().collect()]
        h_filter = [r["x"] for r in par.dfilter(
            lambda x: x % 3 == 0, healthy).collect_frame().collect()]
        h_sort = [r["x"] for r in par.dsort(
            "x", healthy, descending=True).collect_frame().collect()]
        h_red = int(par.dreduce_blocks({"x": "sum"}, healthy)["x"])
        h_agg = par.daggregate({"x": "sum"}, healthy, "k").collect()

        dist = par.distribute(df, mesh8)
        # churn round 1: lose a device mid-op, then re-admit it
        with faults.inject("device", 1):
            out = par.dmap_blocks(lambda x: {"z": x * 2}, dist)
        assert out.mesh.num_devices == 7
        assert [r["z"] for r in out.collect_frame().collect()] == h_map
        dist = par.admit_devices(par.distribute(df, out.mesh))
        assert dist.mesh.num_devices == 8
        # full d-op suite on the regrown mesh
        assert [r["x"] for r in par.dfilter(
            lambda x: x % 3 == 0,
            dist).collect_frame().collect()] == h_filter
        assert [r["x"] for r in par.dsort(
            "x", dist,
            descending=True).collect_frame().collect()] == h_sort
        assert int(par.dreduce_blocks({"x": "sum"}, dist)["x"]) == h_red
        assert par.daggregate({"x": "sum"}, dist, "k").collect() == h_agg
        # churn round 2: lose another device on the regrown mesh
        with faults.inject("device", 1):
            out2 = par.dmap_blocks(lambda x: {"z": x * 2}, dist)
        assert out2.mesh.num_devices == 7
        got = [r["z"] for r in out2.collect_frame().collect()]
        assert got == h_map  # zero lost, zero duplicated
        assert counters.get("mesh.grows") >= 1
        assert counters.get("mesh.devices_lost") == 2
