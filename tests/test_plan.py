"""Logical-plan suite (tier-1; marker ``plan``; ``run-tests.sh --plan``).

The load-bearing contract: **every lazy-op chain is bit-identical fused
and unfused**. Each equivalence case builds the same chain twice — once
under the default (``TFT_FUSE`` unset: fusion, pruning, device-resident
stage chaining) and once under ``TFT_FUSE=0`` (the per-op dispatch
path) — and compares blocks value-for-value, dtype-for-dtype, block
boundaries included. On top of that:

- fusion actually reduces dispatches (pipeline counters);
- error contracts survive: chains the optimizer cannot prove
  row-preserving fall back and raise exactly like the per-op path;
- injected faults (transient dispatch failures, map_rows OOM splits)
  retry/recover THROUGH the fused computation, results still identical;
- plan-node estimates: UNFORCED frames price per column (serve
  admission input), not by the whole-schema row-byte ratio;
- ``explain()`` renders the optimized plan (fused groups, pruned
  columns, resident edges);
- parquet pruning: a chain referencing 2 of 6 columns decodes exactly
  those two (``io._column_to_numpy`` instrumented).
"""

import os

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import io as tio
from tensorframes_tpu.memory.estimate import frame_estimate
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils import tracing
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.plan


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("TFT_RETRY_MAX_DELAY", "0.01")
    monkeypatch.delenv("TFT_FUSE", raising=False)
    faults.reset()
    yield
    faults.reset()


def _snapshot(frame):
    out = []
    for b in frame.blocks():
        cols = {}
        for n, c in b.columns.items():
            cols[n] = list(c) if not isinstance(c, np.ndarray) else c
        out.append((b.num_rows, cols))
    return out


def _assert_identical(fused, unfused):
    assert len(fused) == len(unfused), "block count differs"
    for i, ((nf, cf), (nu, cu)) in enumerate(zip(fused, unfused)):
        assert nf == nu, f"block {i}: row count {nf} != {nu}"
        assert set(cf) == set(cu), f"block {i}: columns differ"
        for n in cu:
            a, b = cf[n], cu[n]
            if isinstance(b, np.ndarray):
                assert isinstance(a, np.ndarray), (i, n)
                assert a.dtype == b.dtype, (i, n, a.dtype, b.dtype)
                assert a.shape == b.shape, (i, n, a.shape, b.shape)
                assert np.array_equal(a, b), (i, n)
            else:
                assert len(a) == len(b), (i, n)
                for x, y in zip(a, b):
                    if isinstance(y, np.ndarray):
                        assert np.array_equal(np.asarray(x), y), (i, n)
                    else:
                        assert x == y, (i, n)


def _both_ways(monkeypatch, make_frame, build, expect_fused=True):
    """Force build(make_frame()) fused and unfused; assert bit-identity.
    Returns the fused chain frame (plan info inspection)."""
    chain = build(make_frame())
    fused = _snapshot(chain)
    if expect_fused:
        assert chain._plan_info, "expected the fused plan to execute"
    monkeypatch.setenv("TFT_FUSE", "0")
    chain0 = build(make_frame())
    unfused = _snapshot(chain0)
    assert chain0._plan_info is None
    monkeypatch.delenv("TFT_FUSE")
    _assert_identical(fused, unfused)
    return chain


def _frame(parts=4, rows=97):
    rng = np.random.default_rng(7)
    return tft.frame(
        {"x": np.arange(float(rows)),
         "y": rng.random(rows),
         "k": (np.arange(rows) % 5).astype(np.int64),
         "v": rng.random((rows, 3)),
         "s": np.array([f"r{i}" for i in range(rows)], dtype=object)},
        num_partitions=parts)


# ---------------------------------------------------------------------------
# equivalence: fused == TFT_FUSE=0, bit for bit
# ---------------------------------------------------------------------------

class TestEquivalence:
    def test_map_blocks_chain(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda x: {"a": x + 1.0})
              .map_blocks(lambda a, y: {"b": a * y})
              .map_blocks(lambda b: {"c": b - 2.0})))

    def test_chain_with_filter_between_maps(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda x: {"a": x * 2.0})
              .filter(lambda a: a % 4.0 == 0.0)
              .map_blocks(lambda a: {"b": a + 0.5})))

    def test_cross_row_map_blocks_fuses(self, monkeypatch):
        # z = x - mean(x) is cross-row but row-preserving: fusable, and
        # per-block semantics identical because block boundaries are
        # identical on both paths
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda x: {"a": x - x.mean()})
              .map_blocks(lambda a: {"b": a * 3.0})))

    def test_filter_after_row_growing_trim(self, monkeypatch):
        # regression: the mask length is the TRIM output's row count,
        # not the stage input's — when they coincidentally relate the
        # gather must still run (review finding: fused path returned
        # all 2n rows when keep == pre-trim n)
        def make():
            return tft.frame({"x": np.arange(4.0)}, num_partitions=1)
        import jax.numpy as jnp
        _both_ways(monkeypatch, make, lambda df: (
            df.map_blocks(lambda x: {"y": jnp.concatenate([x, x])},
                          trim=True)
              .filter(lambda y: y < 2.0)))

    def test_trim_chain(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.select(["x"])
              .map_blocks(lambda x: {"z": x[: x.shape[0] // 2]}, trim=True)
              .map_blocks(lambda z: {"w": z + 1.0})))

    def test_map_rows_chain(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_rows(lambda v: {"n": (v * v).sum()})
              .map_rows(lambda n: {"m": n + 1.0})
              .select(["n", "m", "s"])))

    def test_mixed_ops_with_select_pruning(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda x, y: {"a": x + y})
              .select(["a", "k", "s"])
              .filter(lambda a: a > 1.0)
              .map_rows(lambda a: {"b": a * 0.5})
              .select(["b", "s"])))

    def test_two_filters(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.filter(lambda x: x > 5.0)
              .filter(lambda x: x < 60.0)
              .map_blocks(lambda x: {"a": x + 1.0})))

    def test_filter_drops_everything(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda x: {"a": x + 1.0})
              .filter(lambda a: a < -1.0)
              .map_blocks(lambda a: {"b": a * 2.0})))

    def test_empty_partitions(self, monkeypatch):
        def make():
            return tft.frame({"x": np.arange(3.0)}, num_partitions=1) \
                .repartition(5)
        _both_ways(monkeypatch, make, lambda df: (
            df.map_blocks(lambda x: {"a": x + 1.0})
              .map_blocks(lambda a: {"b": a * 2.0})))

    def test_single_partition(self, monkeypatch):
        _both_ways(monkeypatch, lambda: _frame(parts=1),
                   lambda df: (df.map_blocks(lambda x: {"a": x + 1.0})
                                 .map_blocks(lambda a: {"b": a * 2.0})))

    def test_vector_columns_through_chain(self, monkeypatch):
        _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda v: {"v2": v * 2.0})
              .filter(lambda x: x % 2.0 == 0.0)
              .select(["v", "v2", "x"])))

    def test_collect_and_count_equal(self, monkeypatch):
        df = _frame()
        chain = df.map_blocks(lambda x: {"a": x + 1.0}) \
                  .filter(lambda a: a > 10.0)
        n1 = chain.count()
        rows1 = chain.collect()
        monkeypatch.setenv("TFT_FUSE", "0")
        chain0 = df.map_blocks(lambda x: {"a": x + 1.0}) \
                   .filter(lambda a: a > 10.0)
        assert chain0.count() == n1
        rows0 = chain0.collect()
        for r1, r0 in zip(rows1, rows0):
            for a, b in zip(r1, r0):
                if isinstance(b, np.ndarray):
                    assert np.array_equal(np.asarray(a), b)
                else:
                    assert a == b

    def test_reduction_over_fused_chain(self, monkeypatch):
        df = _frame()
        out1 = tft.reduce_blocks(
            lambda a_input: {"a": a_input.sum()},
            df.map_blocks(lambda x: {"a": x + 1.0})
              .map_blocks(lambda a: {"a_sq": a * a}).select(["a"]))
        monkeypatch.setenv("TFT_FUSE", "0")
        out0 = tft.reduce_blocks(
            lambda a_input: {"a": a_input.sum()},
            df.map_blocks(lambda x: {"a": x + 1.0})
              .map_blocks(lambda a: {"a_sq": a * a}).select(["a"]))
        assert out1 == out0


# ---------------------------------------------------------------------------
# fallback correctness: unplannable chains keep per-op semantics
# ---------------------------------------------------------------------------

class TestFallback:
    def test_row_count_violation_still_raises(self):
        # not provably row-preserving -> falls back -> the per-op
        # runtime check fires exactly as before
        from tensorframes_tpu.engine.ops import InvalidShapeError
        df = _frame()
        chain = df.select(["x"]) \
                  .map_blocks(lambda x: {"z": x[:2]}) \
                  .map_blocks(lambda z: {"w": z + 1.0})
        with pytest.raises(InvalidShapeError, match="trim"):
            chain.blocks()
        assert chain._plan_info is None

    def test_ragged_inputs_fall_back(self, monkeypatch):
        def make():
            return tft.frame(
                [(1.0, np.arange(2.0)), (2.0, np.arange(5.0))],
                columns=["x", "r"]).analyze()
        chain = _both_ways(
            monkeypatch, make,
            lambda df: (df.map_rows(lambda r: {"n": r.sum()})
                          .map_rows(lambda n: {"m": n * 2.0})),
            expect_fused=False)
        assert chain._plan_info is None  # ragged comp inputs stay per-op

    def test_explicit_executor_disables_planning(self):
        from tensorframes_tpu.engine.executor import BlockExecutor
        df = _frame()
        ex = BlockExecutor()
        chain = df.map_blocks(lambda x: {"a": x + 1.0}, executor=ex) \
                  .map_blocks(lambda a: {"b": a * 2.0}, executor=ex)
        chain.blocks()
        assert chain._plan_info is None

    def test_single_op_stays_per_op(self):
        df = _frame()
        one = df.map_blocks(lambda x: {"a": x + 1.0})
        one.blocks()
        assert one._plan_info is None

    def test_empty_final_schema_stays_per_op(self, monkeypatch):
        # select([]) after a row-changing trim: a zero-output fused
        # program cannot carry the trimmed row count, so the chain must
        # stay per-op — and count() must report the TRIMMED rows
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        chain = df.map_blocks(lambda x: {"z": x[: x.shape[0] // 2]},
                              trim=True) \
                  .map_blocks(lambda z: {"w": z + 1.0}).select([])
        n1 = chain.count()
        assert chain._plan_info is None
        monkeypatch.setenv("TFT_FUSE", "0")
        chain0 = df.map_blocks(lambda x: {"z": x[: x.shape[0] // 2]},
                               trim=True) \
                   .map_blocks(lambda z: {"w": z + 1.0}).select([])
        assert chain0.count() == n1 == 4

    def test_fuse_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TFT_FUSE", "0")
        df = _frame()
        chain = df.map_blocks(lambda x: {"a": x + 1.0}) \
                  .map_blocks(lambda a: {"b": a * 2.0})
        chain.blocks()
        assert chain._plan_info is None


# ---------------------------------------------------------------------------
# the point of it all: fewer dispatches
# ---------------------------------------------------------------------------

class TestDispatchReduction:
    def test_fused_chain_is_one_dispatch_per_block(self, monkeypatch):
        df = _frame(parts=4)
        df.cache()

        def pipeline_units(build):
            before = counters.get("pipeline.submitted") \
                + counters.get("pipeline.drained")
            build().blocks()
            return (counters.get("pipeline.submitted")
                    + counters.get("pipeline.drained")) - before

        fused_units = pipeline_units(lambda: (
            df.map_blocks(lambda x: {"a": x + 1.0})
              .map_blocks(lambda a: {"b": a * 2.0})
              .map_blocks(lambda b: {"c": b - 1.0})
              .map_blocks(lambda c: {"d": c * 0.5})))
        monkeypatch.setenv("TFT_FUSE", "0")
        unfused_units = pipeline_units(lambda: (
            df.map_blocks(lambda x: {"a": x + 1.0})
              .map_blocks(lambda a: {"b": a * 2.0})
              .map_blocks(lambda b: {"c": b - 1.0})
              .map_blocks(lambda c: {"d": c * 0.5})))
        # 4 ops over 4 blocks: per-op streams 4x the blocks the fused
        # single stage does
        assert unfused_units >= 4 * fused_units > 0

    def test_device_resident_stage_chaining(self, monkeypatch):
        # map -> filter -> map: two stages; the second stage's input is
        # the first's device output (no host round trip). Proven by
        # bit-identity plus the stage structure in the plan rendering.
        chain = _both_ways(monkeypatch, _frame, lambda df: (
            df.map_blocks(lambda x: {"a": x * 2.0})
              .filter(lambda a: a > 10.0)
              .map_blocks(lambda a: {"b": a + 1.0})))
        text = "\n".join(chain._plan_info)
        assert "device-resident" in text
        assert "mask applied host-side" in text


# ---------------------------------------------------------------------------
# resilience composition on the fused computation
# ---------------------------------------------------------------------------

class TestFusedResilience:
    def test_transient_dispatch_fault_retries_through_fused(
            self, monkeypatch):
        df = _frame()
        expected = _snapshot(df.map_blocks(lambda x: {"a": x + 1.0})
                               .map_blocks(lambda a: {"b": a * 2.0}))
        chain = df.map_blocks(lambda x: {"a": x + 1.0}) \
                  .map_blocks(lambda a: {"b": a * 2.0})
        with faults.inject("dispatch", fail_n=2):
            got = _snapshot(chain)
        assert chain._plan_info, "fused path expected"
        _assert_identical(got, expected)

    def test_oom_split_operates_on_fused_map_rows(self, monkeypatch):
        # a pure-map_rows stage keeps the padding executor, so the
        # reactive OOM split recovers the fused computation too
        df = tft.frame({"x": np.arange(64.0)}, num_partitions=1)
        expected = _snapshot(df.map_rows(lambda x: {"a": x + 1.0})
                               .map_rows(lambda a: {"b": a * 2.0}))
        before = counters.get("oom_split.dispatches")
        chain = df.map_rows(lambda x: {"a": x + 1.0}) \
                  .map_rows(lambda a: {"b": a * 2.0})
        with faults.inject("oom", fail_n=1):
            got = _snapshot(chain)
        assert chain._plan_info, "fused path expected"
        assert counters.get("oom_split.dispatches") > before
        _assert_identical(got, expected)

    def test_oom_on_unsplittable_stage_falls_back_to_per_op(self):
        # a stage with a filter member cannot legally split; an OOM
        # there must hand the forcing back to the per-op path (which
        # recovers with its op-granular machinery) instead of failing
        # a query TFT_FUSE=0 survives
        df = tft.frame({"v": np.arange(64.0)}, num_partitions=1)
        expected = (np.arange(64.0) + 1.0) * 2.0
        chain = df.map_rows(lambda v: {"a": v + 1.0}) \
                  .filter(lambda a: a > 0.0) \
                  .map_rows(lambda a: {"b": a * 2.0})
        before = counters.get("plan.oom_fallbacks")
        with faults.inject("oom", fail_n=1):
            out = chain.blocks()
        assert counters.get("plan.oom_fallbacks") > before
        got = np.concatenate([b.columns["b"] for b in out])
        assert np.array_equal(got, expected)

    def test_permanent_fault_still_raises(self):
        df = _frame()
        chain = df.map_blocks(lambda x: {"a": x + 1.0}) \
                  .map_blocks(lambda a: {"b": a * 2.0})
        with faults.inject("dispatch", fail_n=100):
            with pytest.raises(Exception):
                chain.blocks()


# ---------------------------------------------------------------------------
# plan-derived estimates (serve admission input)
# ---------------------------------------------------------------------------

class TestPlanEstimates:
    def test_select_prices_per_column_not_schema_ratio(self):
        rows = 1000
        df = tft.frame({"x": np.arange(float(rows)),
                        "v": np.ones((rows, 8))}, num_partitions=2)
        sel = df.select(["x"])
        est_rows, est_bytes = frame_estimate(sel)
        assert est_rows == rows
        # per-column accounting: exactly x's bytes, not total * ratio
        assert est_bytes == rows * 8

    def test_map_adds_fetch_bytes(self):
        rows = 500
        df = tft.frame({"x": np.arange(float(rows))}, num_partitions=2)
        chain = df.map_blocks(lambda x: {"a": x + 1.0})
        est_rows, est_bytes = frame_estimate(chain)
        assert est_rows == rows
        assert est_bytes == 2 * rows * 8  # x + the new fetch column

    def test_unforced_serve_estimate_comes_from_plan(self):
        # what serve.scheduler._estimate consumes for admission. A
        # long-string column makes the old ratio heuristic (strings
        # count an 8-byte pointer in schema_row_bytes) wildly wrong;
        # the per-column model subtracts the string's MEASURED bytes.
        rows = 256
        df = tft.frame({"x": np.arange(float(rows)),
                        "pad": np.ones((rows, 16))}, num_partitions=2)
        chain = df.select(["x"]).map_blocks(lambda x: {"a": x * 2.0})
        assert chain._cache is None
        # the plan node is the source of truth: zero out the scalar
        # hints the pre-plan heuristic lived on and the estimate is
        # still exact, per column
        chain._rows_hint = None
        chain._bytes_hint = None
        est_rows, est_bytes = frame_estimate(chain)
        assert est_rows == rows
        assert est_bytes == 2 * rows * 8  # x + a; pad pruned away

    def test_filter_estimate_is_upper_bound(self):
        df = tft.frame({"x": np.arange(100.0)}, num_partitions=2)
        chain = df.filter(lambda x: x > 1e9) \
                  .map_blocks(lambda x: {"a": x + 1.0})
        est_rows, _ = frame_estimate(chain)
        assert est_rows == 100  # upper bound, same contract as before


# ---------------------------------------------------------------------------
# explain() renders the plan
# ---------------------------------------------------------------------------

class TestExplain:
    def test_plan_section_in_explain(self):
        df = _frame()
        chain = df.map_blocks(lambda x: {"a": x + 1.0}) \
                  .filter(lambda a: a > 2.0) \
                  .map_blocks(lambda a: {"b": a * 2.0})
        tracing.enable()
        try:
            chain.blocks()
            report = chain.explain()
        finally:
            tracing.disable()
        assert "plan" in report
        assert "fused stage" in report
        assert "1 dispatch/block" in report

    def test_no_plan_section_when_fusion_off(self, monkeypatch):
        monkeypatch.setenv("TFT_FUSE", "0")
        df = _frame()
        chain = df.map_blocks(lambda x: {"a": x + 1.0}) \
                  .map_blocks(lambda a: {"b": a * 2.0})
        tracing.enable()
        try:
            chain.blocks()
            report = chain.explain()
        finally:
            tracing.disable()
        assert "fused stage" not in report


# ---------------------------------------------------------------------------
# parquet pruning end to end
# ---------------------------------------------------------------------------

class TestParquetPruning:
    @pytest.fixture
    def six_col_file(self, tmp_path):
        path = str(tmp_path / "six.parquet")
        cols = {f"c{i}": np.arange(40.0) + 10 * i for i in range(6)}
        tio.write_parquet(tft.frame(cols, num_partitions=4), path)
        return path, cols

    def test_chain_reads_only_referenced_columns(self, six_col_file,
                                                 monkeypatch):
        path, cols = six_col_file
        decoded = []
        import tensorframes_tpu.io as io_mod
        real = io_mod._column_to_numpy
        monkeypatch.setattr(io_mod, "_column_to_numpy",
                            lambda col, name: decoded.append(name)
                            or real(col, name))
        chain = tio.read_parquet(path) \
            .map_blocks(lambda c1, c4: {"s": c1 + c4}).select(["s"])
        out = chain.blocks()
        assert chain._plan_info
        assert "pruned" in "\n".join(chain._plan_info)
        assert set(decoded) == {"c1", "c4"}
        got = np.concatenate([b.columns["s"] for b in out])
        assert np.array_equal(got, cols["c1"] + cols["c4"])

    def test_pruned_chain_equals_unfused(self, six_col_file, monkeypatch):
        path, _ = six_col_file
        _both_ways(
            monkeypatch, lambda: tio.read_parquet(path),
            lambda df: (df.map_blocks(lambda c0, c2: {"s": c0 * c2})
                          .filter(lambda s: s > 100.0)
                          .select(["s", "c0"])))

    def test_select_only_chain_prunes_scan(self, six_col_file,
                                           monkeypatch):
        path, cols = six_col_file
        decoded = []
        import tensorframes_tpu.io as io_mod
        real = io_mod._column_to_numpy
        monkeypatch.setattr(io_mod, "_column_to_numpy",
                            lambda col, name: decoded.append(name)
                            or real(col, name))
        sel = tio.read_parquet(path).select(["c3"])
        out = sel.blocks()
        assert set(decoded) == {"c3"}
        assert np.array_equal(
            np.concatenate([b.columns["c3"] for b in out]), cols["c3"])

    def test_empty_row_group_with_pruned_mid_select(self, tmp_path,
                                                    monkeypatch):
        # regression: a 0-row row group's replay walks the per-op chain,
        # whose mid-chain select names a PRUNED column — the empty leaf
        # block must be widened back to the full leaf schema first
        path = str(tmp_path / "er.parquet")
        src = tft.frame({"a": np.arange(3.0), "b": np.ones(3)},
                        num_partitions=1).repartition(4)  # one 0-row blk
        tio.write_parquet(src, path)
        _both_ways(
            monkeypatch, lambda: tio.read_parquet(path),
            lambda df: (df.select(["a", "b"])
                          .map_rows(lambda a: {"x": a * 2.0})
                          .select(["x"])))

    def test_forcing_leaf_directly_reads_everything(self, six_col_file):
        path, cols = six_col_file
        df = tio.read_parquet(path)
        blocks = df.blocks()
        assert set(blocks[0].columns) == set(cols)
        assert df.num_partitions == 4
