"""Resilience layer: retries, deadlines, fault injection, degradation.

Every recovery path the resilience subsystem promises is proven
end-to-end here on the CPU backend, driven by the deterministic fault
harness (``tensorframes_tpu.resilience.faults``) — no real TPU failures
or clusters required. None of these are ``slow``; the whole file also
runs standalone via the ``resilience`` marker lane in ``run-tests.sh``.
"""

import os
import threading
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from conftest import timing_margin
from tensorframes_tpu import resilience as rz
from tensorframes_tpu.engine.executor import BlockExecutor
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _fast_and_clean(monkeypatch):
    """Millisecond backoffs + clean counters/faults for every test."""
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("TFT_RETRY_MAX_DELAY", "0.01")
    counters.reset()
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# policy + deadline primitives
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            faults.check("unit")
            return 42

        with faults.inject("unit", fail_n=2):
            out = rz.RetryPolicy(max_attempts=3, base_delay=0.001).call(
                flaky, op="unit")
        assert out == 42
        assert len(calls) == 3
        assert counters.get("retry.unit.retries") == 2
        assert counters.get("retry.unit.giveups") == 0

    def test_gives_up_and_raises_last(self):
        with faults.inject("unit", fail_n=10):
            with pytest.raises(rz.InjectedFault):
                rz.RetryPolicy(max_attempts=2, base_delay=0.001).call(
                    lambda: faults.check("unit"), op="unit")
        assert counters.get("retry.unit.retries") == 1
        assert counters.get("retry.unit.giveups") == 1

    def test_permanent_errors_not_retried(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("shape mismatch: deterministic, do not retry")

        with pytest.raises(ValueError):
            rz.RetryPolicy(max_attempts=5).call(broken, op="unit")
        assert len(calls) == 1
        assert counters.get("retry.unit.retries") == 0

    def test_backoff_is_deterministic_and_bounded(self):
        p = rz.RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.5,
                           jitter=0.25)
        delays = [p.backoff(i, op="x") for i in range(6)]
        assert delays == [p.backoff(i, op="x") for i in range(6)]
        assert all(d <= 0.5 * 1.25 + 1e-9 for d in delays)
        assert p.backoff(0, op="x") != p.backoff(0, op="y") or True
        # no-jitter policy is exactly exponential-with-cap
        p0 = rz.RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.5,
                            jitter=0.0)
        assert [round(p0.backoff(i), 3) for i in range(4)] == \
            [0.1, 0.2, 0.4, 0.5]

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("TFT_RETRY_MAX_ATTEMPTS", "7")
        monkeypatch.setenv("TFT_RETRY_DEADLINE", "12.5")
        p = rz.default_policy()
        assert p.max_attempts == 7
        assert p.deadline == 12.5


@pytest.mark.timing
class TestDeadline:
    def test_expiry_raises_within_budget(self):
        t0 = time.monotonic()
        with pytest.raises(rz.DeadlineExceeded):
            with rz.deadline(0.05):
                with faults.inject("unit", fail_n=100):
                    rz.RetryPolicy(max_attempts=100,
                                   base_delay=0.02).call(
                        lambda: faults.check("unit"), op="unit")
        # generous margin over the 0.05s budget: the bound proves the
        # loop STOPPED, not that the box was idle — concurrent suite
        # load must not flake it (marker `timing`; TFT_TIMING_MARGIN
        # widens it further)
        assert time.monotonic() - t0 < timing_margin(3.0)

    def test_nested_deadlines_only_shrink(self):
        with rz.deadline(10.0):
            with rz.deadline(0.01):
                left = rz.remaining_time()
                assert left is not None and left <= 0.011
            outer_left = rz.remaining_time()
            assert outer_left is not None and outer_left > 1.0

    def test_check_deadline_counts(self):
        with rz.deadline(0.001):
            time.sleep(0.005)
            with pytest.raises(rz.DeadlineExceeded):
                rz.policy.check_deadline("op_x")
        assert counters.get("deadline.op_x.expired") == 1


class TestFaults:
    def test_budget_is_exact(self):
        with faults.inject("unit", fail_n=2):
            for _ in range(2):
                with pytest.raises(rz.InjectedFault):
                    faults.check("unit")
            faults.check("unit")  # third passes
        faults.check("unit")  # disarmed on exit

    def test_env_driven(self, monkeypatch):
        monkeypatch.setenv("TFT_FAULTS", "envsite:1")
        # re-arm parsing is once-per-process; force it for the test
        faults._state._armed_env = False
        with pytest.raises(rz.InjectedFault):
            faults.check("envsite")
        faults.check("envsite")

    def test_oom_site_is_oom_shaped(self):
        with faults.inject("oom", fail_n=1):
            with pytest.raises(rz.InjectedFault) as ei:
                faults.check("oom")
        assert rz.is_oom(ei.value)
        assert not rz.is_transient(ei.value)


# ---------------------------------------------------------------------------
# engine: dispatch retry, padded-compile fallback, OOM split
# ---------------------------------------------------------------------------

class TestEngineResilience:
    def test_map_blocks_succeeds_on_third_attempt(self, monkeypatch):
        """Acceptance: inject("compile", fail_n=2) → a 3-block map
        succeeds on the 3rd attempt and exactly 2 retries are recorded.

        Pinned to the serial engine: under pipelining an async-submit
        fault is recovered by a sync re-run instead of an in-place retry
        (tests/test_pipeline.py covers that composition)."""
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "1")
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        with faults.inject("compile", fail_n=2):
            out = df.map_blocks(lambda x: {"y": x * 2.0}).collect()
        got = np.concatenate([np.atleast_1d(r["y"]) for r in out])
        np.testing.assert_allclose(np.sort(got), np.arange(12.0) * 2.0)
        assert counters.get("retry.executor.dispatch.retries") == 2
        assert counters.get("retry.executor.dispatch.giveups") == 0

    def test_dispatch_gives_up_after_max_attempts(self, monkeypatch):
        monkeypatch.setenv("TFT_RETRY_MAX_ATTEMPTS", "2")
        df = tft.frame({"x": np.arange(4.0)}, num_partitions=1)
        with faults.inject("dispatch", fail_n=10):
            with pytest.raises(rz.InjectedFault):
                df.map_blocks(lambda x: {"y": x + 1.0}).collect()
        assert counters.get("retry.executor.dispatch.giveups") == 1

    def test_padded_compile_falls_back_to_exact_shape(self):
        # 7 rows pads to the 8-bucket; the bucketed compile fails once,
        # the exact shape must still produce correct results
        df = tft.frame({"x": np.arange(7.0)}, num_partitions=1)
        with faults.inject("pad_compile", fail_n=1):
            out = df.map_rows(lambda x: {"y": x + 10.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_allclose(got, np.arange(7.0) + 10.0)
        assert counters.get("pad_fallback.compiles") == 1

    def test_oom_triggers_split_block_redispatch(self):
        df = tft.frame({"x": np.arange(16.0)}, num_partitions=1)
        with faults.inject("oom", fail_n=1):
            out = df.map_rows(lambda x: {"y": x * 3.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_allclose(got, np.arange(16.0) * 3.0)
        assert counters.get("oom_split.dispatches") == 1

    def test_oom_split_recurses_until_it_fits(self):
        # two consecutive OOMs: 16 -> 8 (OOM again) -> 4+4, then clean
        df = tft.frame({"x": np.arange(16.0)}, num_partitions=1)
        with faults.inject("oom", fail_n=2):
            out = df.map_rows(lambda x: {"y": x + 1.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_allclose(got, np.arange(16.0) + 1.0)
        assert counters.get("oom_split.dispatches") == 2

    def test_oom_split_halves_run_exact_below_min_bucket(self):
        # 5 rows pads to the 8-bucket; after the padded dispatch OOMs the
        # 2/3-row halves must run at their EXACT shapes — re-padding them
        # back up to the same 8-bucket would dispatch the identical
        # program, OOM identically, and the recovery could never succeed
        ex = BlockExecutor(pad_rows=True)
        df = tft.frame({"x": np.arange(5.0)}, num_partitions=1)
        with faults.inject("oom", fail_n=1):
            out = df.map_rows(lambda x: {"y": x + 1.0},
                              executor=ex).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_allclose(got, np.arange(5.0) + 1.0)
        assert counters.get("oom_split.dispatches") == 1
        # padded-8 compile + exact 2-row + exact 3-row (a re-padding
        # regression would cache-hit the 8-bucket and stay at 1)
        assert ex.compile_count == 3

    def test_padding_executor_pad_fallback_oom_still_splits(self):
        # double failure on the composable padding wrapper: the bucketed
        # compile dies (non-OOM) AND the exact-shape fallback OOMs — the
        # path is still row-local, so the split must engage, not the
        # job die (degradation matrix: row-local OOM -> split)
        from tensorframes_tpu.engine.executor import PaddingExecutor
        ex = PaddingExecutor(BlockExecutor())
        df = tft.frame({"x": np.arange(5.0)}, num_partitions=1)
        with faults.inject("pad_compile", fail_n=1):
            with faults.inject("oom", fail_n=1):
                out = df.map_rows(lambda x: {"y": x + 1.0},
                                  executor=ex).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_allclose(got, np.arange(5.0) + 1.0)
        assert counters.get("pad_fallback.compiles") == 1
        assert counters.get("oom_split.dispatches") == 1

    def test_oom_without_row_local_contract_propagates(self):
        # block-level computations may be cross-row: splitting would be
        # WRONG, so the OOM must propagate (degradation matrix: fail fast)
        ex = BlockExecutor()  # pad_rows=False: no row-locality promise
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=1)
        with faults.inject("oom", fail_n=1):
            with pytest.raises(rz.InjectedFault):
                df.map_blocks(lambda x: {"y": x - x.mean()},
                              executor=ex).collect()

    def test_oom_split_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("TFT_OOM_SPLIT", "0")
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=1)
        with faults.inject("oom", fail_n=1):
            with pytest.raises(rz.InjectedFault):
                df.map_rows(lambda x: {"y": x + 1.0}).collect()


# ---------------------------------------------------------------------------
# compile cache thread-safety under concurrent dispatch
# ---------------------------------------------------------------------------

class TestConcurrentDispatch:
    def test_concurrent_dispatch_compiles_each_signature_once(self):
        """Many threads, few signatures: the signature→executable dict
        must neither lose entries nor compile duplicates (the guarded
        double-checked locking contract in BlockExecutor._compiled)."""
        ex = BlockExecutor()
        comp = None
        df = tft.frame({"x": np.arange(4.0)})
        from tensorframes_tpu.engine import ops as _ops

        comp = _ops._map_computation(lambda x: {"y": x * 2.0}, df.schema,
                                     block_level=True)
        sizes = [3, 5, 8, 13]  # 4 distinct signatures
        errs = []
        results = {}

        def work(i):
            try:
                n = sizes[i % len(sizes)]
                out = ex.run(comp, {"x": np.arange(float(n))})
                results[i] = out["y"]
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(e)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert ex.compile_count == len(sizes)
        for i, y in results.items():
            n = sizes[i % len(sizes)]
            np.testing.assert_allclose(y, np.arange(float(n)) * 2.0)


# ---------------------------------------------------------------------------
# cluster bootstrap
# ---------------------------------------------------------------------------

class TestClusterResilience:
    def test_partial_env_raises_valueerror(self, monkeypatch):
        from tensorframes_tpu.parallel import cluster

        monkeypatch.setenv("TFT_COORDINATOR", "127.0.0.1:9999")
        monkeypatch.delenv("TFT_NUM_PROCESSES", raising=False)
        monkeypatch.delenv("TFT_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="partially-specified"):
            cluster.initialize()

    def test_partial_args_raise_valueerror(self):
        from tensorframes_tpu.parallel import cluster

        with pytest.raises(ValueError, match="TFT_NUM_PROCESSES"):
            cluster.initialize(coordinator_address="127.0.0.1:9999")

    def test_malformed_coordinator_address_fails_fast(self):
        # a typo'd address must fail like the partial spec above, not
        # burn the bootstrap deadline retrying a doomed probe and then
        # silently degrade (split-brain)
        from tensorframes_tpu.parallel import cluster

        with pytest.raises(ValueError, match="host:port"):
            cluster.initialize(coordinator_address="tpu-host",
                               num_processes=2, process_id=1)

    def test_hostport_parses_bracketed_ipv6(self):
        from tensorframes_tpu.parallel.cluster import _parse_hostport

        assert _parse_hostport("[fd00::1]:1234") == ("fd00::1", 1234)
        assert _parse_hostport("10.0.0.2:99") == ("10.0.0.2", 99)
        with pytest.raises(ValueError):
            _parse_hostport("10.0.0.2")
        with pytest.raises(ValueError):
            _parse_hostport("host:notaport")

    def test_fault_injected_bootstrap_retries_then_degrades(self):
        from tensorframes_tpu.parallel import cluster

        with faults.inject("cluster_init", fail_n=10):
            ok = cluster.initialize(timeout=2)
        assert ok is False
        assert counters.get("retry.cluster_init.retries") >= 1
        assert counters.get("cluster_init.degraded") == 1

    def test_fault_injected_bootstrap_retries_then_succeeds(self):
        from tensorframes_tpu.parallel import cluster

        # two scripted failures, then the (single-process autodetect)
        # attempt proceeds; degradation must NOT be recorded
        with faults.inject("cluster_init", fail_n=2):
            cluster.initialize(timeout=5)
        assert counters.get("retry.cluster_init.retries") == 2
        assert counters.get("cluster_init.degraded") == 0

    @pytest.mark.timing
    def test_require_cluster_fails_fast_on_unreachable_coordinator(
            self, monkeypatch):
        """Acceptance: TFT_REQUIRE_CLUSTER=1 + unreachable coordinator →
        initialize() raises within the configured deadline, no hang."""
        from tensorframes_tpu.parallel import cluster

        monkeypatch.setenv("TFT_REQUIRE_CLUSTER", "1")
        t0 = time.monotonic()
        with pytest.raises(rz.ClusterInitError):
            cluster.initialize("127.0.0.1:1", 2, 1, timeout=3)
        # the deadline bounds when the loop STOPS retrying; the attempt
        # in flight at expiry still finishes (one socket connect, ~ms) —
        # a wide margin so a loaded machine can't flake the bound
        # (marker `timing`; TFT_TIMING_MARGIN widens it further): the
        # assertion distinguishes "stopped after its 3s deadline" from
        # "hung", nothing finer
        assert time.monotonic() - t0 < timing_margin(5.0)
        assert counters.get("cluster_init.failures") == 1

    def test_unreachable_coordinator_degrades_without_require(
            self, monkeypatch):
        from tensorframes_tpu.parallel import cluster

        monkeypatch.delenv("TFT_REQUIRE_CLUSTER", raising=False)
        ok = cluster.initialize("127.0.0.1:1", 2, 1, timeout=2)
        assert ok is False
        assert counters.get("cluster_init.degraded") == 1


# ---------------------------------------------------------------------------
# mesh dispatch
# ---------------------------------------------------------------------------

class TestMeshResilience:
    def test_dmap_retries_transient_failures(self):
        from tensorframes_tpu import parallel as par
        from tensorframes_tpu.parallel.mesh import local_mesh

        mesh = local_mesh(4)
        df = tft.frame({"x": np.arange(8.0)})
        dist = par.distribute(df, mesh)
        with faults.inject("dmap", fail_n=1):
            out = par.dmap_blocks(lambda x: {"y": x + 1.0}, dist)
        back = out.collect_frame()
        got = np.sort(np.asarray([r["y"] for r in back.collect()],
                                 float).ravel())
        np.testing.assert_allclose(got, np.arange(8.0) + 1.0)
        assert counters.get("retry.dmap_blocks.dispatch.retries") == 1
