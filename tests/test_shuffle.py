"""Shuffle exchange suite: hash repartition + everything built on it.

The acceptance spine (ISSUE 17 / ROADMAP item 3):

- **exchange invariants**: every row lands on exactly one shard, the
  shard is the one the host splitmix64 predicts (``hash(key) % S`` —
  stable for a fixed shard count), string ride-alongs follow their
  rows, received rows keep original global row order per shard, and
  zero rows are lost or duplicated under an injected ``device:1`` loss
  (``elastic_call`` shrink/reshard/re-run);
- **partitioned hash join** is BIT-IDENTICAL to the broadcast oracle
  across the equivalence suite — inner/left, duplicate keys,
  multi-key, string ride-alongs, string KEYS, vector cells, empty
  sides, filter-to-zero — with per-device build bytes O(R/S);
- **shuffle daggregate** matches ``daggregate`` exactly for discrete
  combiners, and the high-cardinality auto-route fires past
  ``TFT_SHUFFLE_AGG_GROUPS``;
- **TFT_SHUFFLE=0** restores the old routing (sort-merge for numeric
  oversized builds, broadcast for string keys) bit-identically;
- the routing decision is flight-recorded (``relational.join_route``)
  and rendered by ``explain()``; exchange skew shows up as
  ``mesh.exchange_*`` counters and an ``explain()`` imbalance line.

No deadline-sensitive assertions here — nothing needs the ``timing``
marker.
"""

import jax
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import memory as tmem
from tensorframes_tpu import parallel as par
from tensorframes_tpu import relational as rel
from tensorframes_tpu.engine.ops import InvalidTypeError
from tensorframes_tpu.observability import flight
from tensorframes_tpu.parallel.exchange import (dexchange,
                                                exchange_hash_host,
                                                shuffle_daggregate)
from tensorframes_tpu.relational.join import (broadcast_join, join,
                                              partitioned_hash_join)
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.shuffle


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return par.local_mesh(8)


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    yield
    faults.reset()
    tmem._reset()


def _snap(key):
    return counters.snapshot().get(key, 0)


def _rows(df):
    """NaN-stable row tuples (left-join fills compare equal)."""
    out = []
    for r in df.collect():
        row = []
        for x in r:
            a = np.asarray(x)
            if a.dtype.kind == "f":
                a = np.where(np.isnan(a), np.float64(1.25e300), a)
            row.append(tuple(a.tolist()) if a.ndim else
                       (a.item() if a.dtype.kind != "O" else x))
        out.append(tuple(row))
    return out


def _shard_rows(ex, name):
    """Per-shard valid slices of one column of an exchanged frame."""
    S = ex.mesh.num_data_shards
    rp = ex.padded_rows // S
    col = ex.host_read_padded(name)
    valid = ex.per_shard_valid()
    return [col[s * rp: s * rp + int(valid[s])] for s in range(S)]


def _frames(rng, nl=400, nr=160, multi=False, vec=False):
    lk = rng.integers(0, 60, nl).astype(np.int64)
    rk = rng.integers(0, 60, nr).astype(np.int64)
    lc = {"k": lk, "lv": rng.standard_normal(nl),
          "ltag": np.array([f"L{i}" for i in range(nl)], object)}
    rc = {"k": rk, "rv": rng.standard_normal(nr),
          "rtag": np.array([f"R{i}" for i in range(nr)], object)}
    if multi:
        lc["k2"] = rng.integers(0, 3, nl).astype(np.int64)
        rc["k2"] = rng.integers(0, 3, nr).astype(np.int64)
    if vec:
        rc["rvec"] = rng.standard_normal((nr, 4))
    return (tft.frame(lc, num_partitions=3),
            tft.frame(rc, num_partitions=2))


# ---------------------------------------------------------------------------
# exchange placement / conservation properties
# ---------------------------------------------------------------------------

class TestExchangeInvariants:
    def test_placement_matches_host_hash(self, mesh8, rng):
        keys = rng.integers(-500, 500, 700).astype(np.int64)
        df = tft.frame({"k": keys, "v": rng.standard_normal(700)})
        ex = dexchange("k", par.distribute(df, mesh8))
        pred = (exchange_hash_host([keys]) % np.uint64(8)).astype(int)
        shards = _shard_rows(ex, "k")
        # every row on exactly one shard — and the predicted one
        assert sum(len(s) for s in shards) == 700
        for s, got in enumerate(shards):
            want = keys[pred == s]
            assert np.array_equal(got, want), f"shard {s}"

    def test_placement_stable_and_colocating(self, mesh8, rng):
        # same values, different frames/order -> same shard per value
        vals = rng.integers(0, 100, 300).astype(np.int64)
        a = dexchange("k", par.distribute(tft.frame({"k": vals}), mesh8))
        b = dexchange("k", par.distribute(
            tft.frame({"k": vals[::-1].copy()}), mesh8))
        for s in range(8):
            sa = set(_shard_rows(a, "k")[s].tolist())
            sb = set(_shard_rows(b, "k")[s].tolist())
            assert sa == sb

    def test_string_keys_and_ride_alongs(self, mesh8, rng):
        n = 250
        sk = np.array([f"key-{i % 37}" for i in range(n)], object)
        tag = np.array([f"row{i}" for i in range(n)], object)
        v = np.arange(n, dtype=np.int64)
        ex = dexchange("s", par.distribute(
            tft.frame({"s": sk, "v": v, "tag": tag}), mesh8))
        vs = _shard_rows(ex, "v")
        assert sum(len(x) for x in vs) == n
        got_tags = []
        for s in range(8):
            ss = _shard_rows(ex, "s")[s]
            ts = _shard_rows(ex, "tag")[s]
            vv = vs[s]
            # the string ride-alongs followed their rows
            for si, ti, vi in zip(ss, ts, vv):
                assert si == sk[vi] and ti == tag[vi]
            got_tags.extend(ts)
        assert sorted(got_tags) == sorted(tag.tolist())

    def test_per_shard_original_order(self, mesh8, rng):
        keys = rng.integers(0, 40, 500).astype(np.int64)
        ex = dexchange("k", par.distribute(tft.frame(
            {"k": keys, "i": np.arange(500, dtype=np.int64)}), mesh8))
        for s in range(8):
            idx = _shard_rows(ex, "i")[s]
            assert np.all(np.diff(idx) > 0), \
                f"shard {s} not in original row order"

    def test_float_and_multi_key(self, mesh8, rng):
        # -0.0 / 0.0 and NaN canonicalize to one destination
        f = np.array([0.0, -0.0, np.nan, np.nan, 1.5, 1.5], np.float64)
        g = np.array([1, 1, 2, 2, 3, 3], np.int64)
        ex = dexchange(["f", "g"], par.distribute(
            tft.frame({"f": f, "g": g}), mesh8))
        assert int(ex.per_shard_valid().sum()) == 6
        fs = _shard_rows(ex, "f")
        for s in range(8):
            gs = _shard_rows(ex, "g")[s]
            # equal (f, g) pairs landed together: 0.0 with -0.0, NaN
            # with NaN
            if 1 in gs:
                assert (gs == 1).sum() == 2
            if 2 in gs:
                assert np.isnan(fs[s][gs == 2]).all()
                assert (gs == 2).sum() == 2

    def test_device_loss_zero_lost_rows(self, mesh8, rng):
        keys = rng.integers(0, 90, 640).astype(np.int64)
        df = tft.frame({"k": keys,
                        "i": np.arange(640, dtype=np.int64)})
        lost0 = _snap("mesh.devices_lost")
        with faults.inject("device", 1):
            ex = dexchange("k", par.distribute(df, mesh8))
        assert _snap("mesh.devices_lost") == lost0 + 1
        S = ex.mesh.num_data_shards
        assert S == 7  # shrunk
        idx = np.concatenate(_shard_rows(ex, "i"))
        assert sorted(idx.tolist()) == list(range(640))  # no loss/dup
        # placement on the SURVIVING count matches the host hash
        pred = (exchange_hash_host([keys]) % np.uint64(S)).astype(int)
        for s in range(S):
            got = _shard_rows(ex, "k")[s]
            assert np.array_equal(got, keys[pred == s])

    def test_single_shard_noop(self, rng):
        m1 = par.local_mesh(1)
        df = tft.frame({"k": np.arange(5, dtype=np.int64)})
        dist = par.distribute(df, m1)
        assert dexchange("k", dist) is dist

    def test_skew_counters_and_explain(self, mesh8, rng):
        flight.clear()
        d0 = _snap("mesh.exchange_dispatches")
        s0 = _snap("mesh.exchange_skew_events")
        keys = np.zeros(400, np.int64)  # all rows -> one shard
        ex = dexchange("k", par.distribute(tft.frame({"k": keys}),
                                           mesh8))
        assert _snap("mesh.exchange_dispatches") == d0 + 1
        assert _snap("mesh.exchange_skew_events") == s0 + 1
        assert _snap("mesh.exchange_rows") >= 400
        recs = [r for r in flight.recent(kind="mesh.exchange_skew")]
        assert recs and recs[-1]["rows"] == 400
        text = ex.explain()
        assert "exchange: partition imbalance" in text
        assert "OVER TFT_SKEW_WARN" in text


# ---------------------------------------------------------------------------
# partitioned hash join vs the broadcast oracle
# ---------------------------------------------------------------------------

class TestPartitionedJoin:
    @pytest.mark.parametrize("how", ["inner", "left"])
    @pytest.mark.parametrize("multi", [False, True])
    def test_broadcast_bit_identity(self, mesh8, rng, how, multi):
        left, right = _frames(rng, multi=multi)
        on = ["k", "k2"] if multi else "k"
        b = broadcast_join(left, right, on, how=how)
        p = partitioned_hash_join(left, right, on, how=how, mesh=mesh8)
        assert b.schema.names == p.schema.names
        assert _rows(b) == _rows(p)
        assert [x.num_rows for x in b.blocks()] \
            == [x.num_rows for x in p.blocks()]

    def test_vector_cells_and_indicator(self, mesh8, rng):
        left, right = _frames(rng, vec=True)
        b = broadcast_join(left, right, "k", how="left", indicator="_m")
        p = partitioned_hash_join(left, right, "k", how="left",
                                  mesh=mesh8, indicator="_m")
        assert _rows(b) == _rows(p)

    @pytest.mark.parametrize("how", ["inner", "left"])
    def test_string_keys(self, mesh8, rng, how):
        ls = np.array([f"u{i % 23}" for i in range(300)], object)
        rs = np.array([f"u{i % 31}" for i in range(120)], object)
        left = tft.frame({"s": ls, "lv": rng.standard_normal(300)},
                         num_partitions=2)
        right = tft.frame({"s": rs, "rv": rng.standard_normal(120)})
        b = broadcast_join(left, right, "s", how=how)
        p = partitioned_hash_join(left, right, "s", how=how, mesh=mesh8)
        assert _rows(b) == _rows(p)

    def test_empty_sides_and_filter_to_zero(self, mesh8, rng):
        left, right = _frames(rng)
        r0 = tft.frame({"k": np.empty(0, np.int64),
                        "rv": np.empty(0), "rtag": np.empty(0, object)})
        b = broadcast_join(left, r0, "k", how="left")
        p = partitioned_hash_join(left, r0, "k", how="left", mesh=mesh8)
        assert _rows(b) == _rows(p)
        lz = left.filter(lambda k: k < -1)  # keeps nothing
        b = broadcast_join(lz, right, "k", how="inner")
        p = partitioned_hash_join(lz, right, "k", how="inner",
                                  mesh=mesh8)
        assert _rows(b) == _rows(p) == []

    def test_build_bytes_o_r_over_s(self, mesh8, rng):
        left, right = _frames(rng, nl=1600, nr=1200)
        p = partitioned_hash_join(left, right, "k", how="inner",
                                  mesh=mesh8)
        p.collect()
        info = p._partitioned_info
        assert info["shards"] == 8
        # each device holds a fraction of the global build, not all
        assert info["max_build_bytes"] * 2 < info["global_build_bytes"]
        assert len(info["build_bytes"]) > 1

    def test_device_loss_bit_identity(self, mesh8, rng):
        left, right = _frames(rng, nl=500, nr=220)
        oracle = _rows(broadcast_join(left, right, "k", how="inner"))
        lost0 = _snap("mesh.devices_lost")
        with faults.inject("device", 1):
            p = partitioned_hash_join(left, right, "k", how="inner",
                                      mesh=mesh8)
            got = _rows(p)
        assert got == oracle
        assert _snap("mesh.devices_lost") == lost0 + 1

    def test_mismatched_key_storage_raises(self, mesh8):
        left = tft.frame({"k": np.arange(4, dtype=np.int64)})
        right = tft.frame({"k": np.arange(4, dtype=np.int32),
                           "v": np.arange(4.0)})
        with pytest.raises(InvalidTypeError, match="cast one side"):
            partitioned_hash_join(left, right, "k", mesh=mesh8)

    def test_kill_switch_falls_back_to_broadcast(self, mesh8, rng,
                                                 monkeypatch):
        monkeypatch.setenv("TFT_SHUFFLE", "0")
        left, right = _frames(rng)
        f0 = _snap("relational.partitioned_fallbacks")
        p = partitioned_hash_join(left, right, "k", how="inner",
                                  mesh=mesh8)
        assert _snap("relational.partitioned_fallbacks") == f0 + 1
        assert p._plan_node.strategy == "broadcast"
        assert _rows(p) == _rows(broadcast_join(left, right, "k",
                                                how="inner"))


# ---------------------------------------------------------------------------
# join() auto-routing + observability
# ---------------------------------------------------------------------------

class TestJoinRouting:
    def test_oversized_build_routes_partitioned(self, mesh8, rng,
                                                monkeypatch):
        monkeypatch.setenv("TFT_BROADCAST_LIMIT_BYTES", "1")
        flight.clear()
        left, right = _frames(rng)
        out = join(left, right, "k", how="inner", mesh=mesh8)
        assert out._plan_node.strategy == "partitioned"
        assert out._join_route["strategy"] == "partitioned"
        recs = [r for r in flight.recent(kind="relational.join_route")]
        assert recs and recs[-1]["strategy"] == "partitioned"
        assert recs[-1]["limit"] == 1
        assert recs[-1]["est_build_bytes"] is not None

    def test_oversized_string_keys_route_partitioned(self, mesh8, rng,
                                                     monkeypatch):
        # satellite 2: string-key builds over the limit now have a
        # distributed option instead of falling back to broadcast
        monkeypatch.setenv("TFT_BROADCAST_LIMIT_BYTES", "1")
        ls = np.array([f"u{i % 9}" for i in range(60)], object)
        rs = np.array([f"u{i % 11}" for i in range(40)], object)
        left = tft.frame({"s": ls, "lv": rng.standard_normal(60)})
        right = tft.frame({"s": rs, "rv": rng.standard_normal(40)})
        out = join(left, right, "s", mesh=mesh8)
        assert out._plan_node.strategy == "partitioned"
        oracle = _rows(broadcast_join(left, right, "s"))
        assert _rows(out) == oracle

    def test_shuffle_off_restores_old_routing(self, mesh8, rng,
                                              monkeypatch):
        monkeypatch.setenv("TFT_BROADCAST_LIMIT_BYTES", "1")
        monkeypatch.setenv("TFT_SHUFFLE", "0")
        left, right = _frames(rng)
        out = join(left, right, "k", mesh=mesh8)
        assert out._plan_node.strategy == "sort_merge"
        # string keys: broadcast (the pre-shuffle behavior)
        ls = np.array([f"u{i % 9}" for i in range(30)], object)
        left2 = tft.frame({"s": ls})
        right2 = tft.frame({"s": ls[:10].copy(),
                            "rv": rng.standard_normal(10)})
        out2 = join(left2, right2, "s", mesh=mesh8)
        assert out2._plan_node.strategy == "broadcast"

    def test_small_build_stays_broadcast(self, mesh8, rng):
        left, right = _frames(rng)
        out = join(left, right, "k", mesh=mesh8)
        assert out._plan_node.strategy == "broadcast"
        assert out._join_route["reason"] == "build fits"

    def test_sort_merge_string_error_names_partitioned(self, mesh8):
        left = tft.frame({"s": np.array(["a", "b"], object)})
        right = tft.frame({"s": np.array(["a"], object),
                           "v": np.arange(1.0)})
        with pytest.raises(InvalidTypeError, match="partitioned"):
            rel.sort_merge_join(left, right, "s", mesh=mesh8)

    def test_unknown_strategy_lists_partitioned(self, mesh8, rng):
        left, right = _frames(rng)
        with pytest.raises(ValueError, match="'partitioned'"):
            join(left, right, "k", strategy="nope", mesh=mesh8)

    def test_explain_renders_route(self, mesh8, rng, monkeypatch):
        monkeypatch.setenv("TFT_BROADCAST_LIMIT_BYTES", "1")
        left, right = _frames(rng)
        out = join(left, right, "k", mesh=mesh8)
        out.collect()
        text = out.explain()
        assert "auto-routed to 'partitioned'" in text
        assert "shuffle  : partitioned build across" in text


# ---------------------------------------------------------------------------
# shuffle daggregate
# ---------------------------------------------------------------------------

class TestShuffleAggregate:
    def test_matches_daggregate(self, mesh8, rng):
        n = 900
        keys = rng.integers(-40, 40, n).astype(np.int64)
        df = tft.frame({"k": keys,
                        "a": rng.integers(0, 1000, n).astype(np.int64),
                        "b": rng.integers(0, 1000, n).astype(np.int64)})
        fetches = {"a": "sum", "b": "min"}
        r1 = par.daggregate(fetches, par.distribute(df, mesh8), ["k"])
        r2 = shuffle_daggregate(fetches, par.distribute(df, mesh8),
                                ["k"])
        assert r1.schema.names == r2.schema.names
        assert _rows(r1) == _rows(r2)

    def test_string_keys_match(self, mesh8, rng):
        n = 400
        g = np.array([f"g{i % 19}" for i in range(n)], object)
        df = tft.frame({"g": g,
                        "v": rng.integers(0, 100, n).astype(np.int64)})
        r1 = par.daggregate({"v": "max"}, par.distribute(df, mesh8),
                            ["g"])
        r2 = shuffle_daggregate({"v": "max"},
                                par.distribute(df, mesh8), ["g"])
        assert _rows(r1) == _rows(r2)

    def test_auto_route_threshold(self, mesh8, rng, monkeypatch):
        n = 600
        keys = np.arange(n, dtype=np.int64)  # every row its own group
        df = tft.frame({"k": keys,
                        "v": rng.integers(0, 9, n).astype(np.int64)})
        monkeypatch.setenv("TFT_SHUFFLE_AGG_GROUPS", "100")
        a0 = _snap("mesh.shuffle_agg_routes")
        r = par.daggregate({"v": "sum"}, par.distribute(df, mesh8),
                           ["k"])
        assert _snap("mesh.shuffle_agg_routes") == a0 + 1
        monkeypatch.setenv("TFT_SHUFFLE", "0")
        r0 = par.daggregate({"v": "sum"}, par.distribute(df, mesh8),
                            ["k"])
        assert _rows(r) == _rows(r0)

    def test_kill_switch_delegates(self, mesh8, rng, monkeypatch):
        monkeypatch.setenv("TFT_SHUFFLE", "0")
        keys = rng.integers(0, 10, 100).astype(np.int64)
        df = tft.frame({"k": keys,
                        "v": rng.integers(0, 9, 100).astype(np.int64)})
        s0 = _snap("mesh.shuffle_daggregates")
        r = shuffle_daggregate({"v": "sum"},
                               par.distribute(df, mesh8), ["k"])
        assert _snap("mesh.shuffle_daggregates") == s0  # delegated
        r1 = par.daggregate({"v": "sum"}, par.distribute(df, mesh8),
                            ["k"])
        assert _rows(r) == _rows(r1)

    def test_device_loss_recovers(self, mesh8, rng):
        n = 500
        keys = rng.integers(0, 30, n).astype(np.int64)
        df = tft.frame({"k": keys,
                        "v": rng.integers(0, 50, n).astype(np.int64)})
        oracle = _rows(par.daggregate({"v": "sum"},
                                      par.distribute(df, mesh8), ["k"]))
        with faults.inject("device", 1):
            got = _rows(shuffle_daggregate(
                {"v": "sum"}, par.distribute(df, mesh8), ["k"]))
        assert got == oracle
