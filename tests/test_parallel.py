"""Distribution tests on the 8-virtual-device CPU mesh (SURVEY.md §4:
multi-device simulation stands in for a TPU slice)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import parallel as par
from tensorframes_tpu.shape import Shape, Unknown


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return par.local_mesh(8)


def test_local_mesh_shape(mesh8):
    assert mesh8.num_data_shards == 8
    assert mesh8.num_devices == 8


def test_distribute_and_collect_roundtrip(mesh8):
    df = tft.frame({"x": np.arange(20.0)}, num_partitions=3)
    dist = par.distribute(df, mesh8)
    assert dist.num_rows == 20
    assert dist.padded_rows == 24  # padded to multiple of 8
    back = dist.collect_frame()
    assert [r["x"] for r in back.collect()] == list(np.arange(20.0))


def test_dmap_blocks_elementwise(mesh8):
    df = tft.frame({"x": np.arange(16.0)})
    dist = par.distribute(df, mesh8)
    out = par.dmap_blocks(lambda x: {"z": x * 2 + 1}, dist)
    rows = out.collect_frame().collect()
    assert [r["z"] for r in rows] == [2 * i + 1 for i in range(16)]
    # sharding is preserved: output z is row-sharded over the mesh
    shardings = {d.device for d in out.columns["z"].addressable_shards}
    assert len(shardings) == 8


def test_dmap_trim_and_collision(mesh8):
    df = tft.frame({"x": np.arange(8.0)})
    dist = par.distribute(df, mesh8)
    out = par.dmap_blocks(lambda x: {"z": x}, dist, trim=True)
    assert out.schema.names == ["z"]
    with pytest.raises(ValueError, match="collides"):
        par.dmap_blocks(lambda x: {"x": x}, dist)


def test_dreduce_collective_sum_min(mesh8):
    # pad rows must be masked to the neutral element: pick values where an
    # unmasked zero pad would corrupt both sum (no) and min (yes)
    vals = np.arange(3.0, 24.0)  # 21 rows, min 3.0, padded to 24
    df = tft.frame({"x": vals})
    dist = par.distribute(df, mesh8)
    out = par.dreduce_blocks({"x": "sum"}, dist)
    assert out["x"] == pytest.approx(vals.sum())
    out = par.dreduce_blocks({"x": "min"}, dist)
    assert out["x"] == pytest.approx(3.0)  # a zero pad row would give 0.0
    out = par.dreduce_blocks({"x": "max"}, dist)
    assert out["x"] == pytest.approx(23.0)


def test_dreduce_collective_vector_column(mesh8):
    v = np.arange(30.0).reshape(10, 3)
    dist = par.distribute(tft.frame({"v": v}), mesh8)
    out = par.dreduce_blocks({"v": "sum"}, dist)
    np.testing.assert_allclose(out["v"], v.sum(axis=0))


def test_dreduce_generic_computation(mesh8):
    # arbitrary (non-monoid-name) combine via the per-device path
    vals = np.arange(1.0, 18.0)
    dist = par.distribute(tft.frame({"x": vals}), mesh8)
    out = par.dreduce_blocks(
        lambda x_input: {"x": jnp.sum(x_input * x_input, axis=0)}, dist)
    # NB: sum-of-squares is not idempotent under re-reduction of partials;
    # use max instead to stay contract-correct:
    out = par.dreduce_blocks(
        lambda x_input: {"x": jnp.max(x_input, axis=0)}, dist)
    assert out["x"] == pytest.approx(17.0)


def test_dreduce_matches_single_host(mesh8):
    vals = np.linspace(-5.0, 7.0, 23)
    df = tft.frame({"x": vals}, num_partitions=4)
    single = tft.reduce_blocks(
        lambda x_input: {"x": jnp.min(x_input, axis=0)}, df)
    dist = par.distribute(df, mesh8)
    multi = par.dreduce_blocks(
        lambda x_input: {"x": jnp.min(x_input, axis=0)}, dist)
    assert multi["x"] == pytest.approx(single)


def test_dreduce_empty_raises(mesh8):
    dist = par.distribute(tft.frame({"x": np.empty(0)}), mesh8)
    with pytest.raises(ValueError, match="empty"):
        par.dreduce_blocks({"x": "sum"}, dist)


def test_ring_allreduce_matches_psum(mesh8):
    x = np.arange(8.0 * 5).reshape(8, 5).astype(np.float32)
    out = np.asarray(par.ring_allreduce(
        jax.device_put(x, mesh8.row_sharding(2)), mesh8))
    expected = np.broadcast_to(x.sum(axis=0), (8, 5))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh8, causal):
    B, S, H, D = 2, 32, 2, 8  # S sharded 8 ways -> 4 per device
    rng = np.random.default_rng(0)
    q = rng.normal(size=(B, S, H, D)).astype(np.float32)
    k = rng.normal(size=(B, S, H, D)).astype(np.float32)
    v = rng.normal(size=(B, S, H, D)).astype(np.float32)

    # reference full attention on one device
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        scores = np.where(mask[None, None], scores, -np.inf)
    w = np.exp(scores - scores.max(axis=-1, keepdims=True))
    w /= w.sum(axis=-1, keepdims=True)
    expected = np.einsum("bhqk,bkhd->bqhd", w, v)

    sharding = jax.sharding.NamedSharding(
        mesh8.mesh, jax.sharding.PartitionSpec(None, "data", None, None))
    qs, ks, vs = (jax.device_put(a, sharding) for a in (q, k, v))
    out = np.asarray(par.ring_attention(qs, ks, vs, mesh8, causal=causal))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# daggregate
# ---------------------------------------------------------------------------

def test_daggregate_matches_host_aggregate(mesh8):
    rng = np.random.default_rng(11)
    n, g = 10_000, 1_000
    keys = rng.integers(0, g, n)
    vals = rng.normal(size=n)
    df = tft.frame({"key": keys, "x": vals}, num_partitions=4)
    host = tft.aggregate({"x": "sum"}, df.group_by("key"))
    dist = par.distribute(df, mesh8)
    mesh_out = par.daggregate({"x": "sum"}, dist, "key")
    h = {r["key"]: r["x"] for r in host.collect()}
    m = {r["key"]: r["x"] for r in mesh_out.collect()}
    assert set(h) == set(m)
    for k in h:
        assert np.isclose(h[k], m[k], rtol=1e-9), k


def test_daggregate_min_max_vector_multi_key(mesh8):
    rng = np.random.default_rng(12)
    k1 = rng.integers(0, 4, 50)
    k2 = rng.integers(0, 3, 50)
    v = rng.normal(size=(50, 2))
    df = tft.frame({"k1": k1, "k2": k2, "v": v})
    dist = par.distribute(df, mesh8)
    out = par.daggregate({"v": "max"}, dist, ["k1", "k2"])
    rows = out.collect()
    for r in rows:
        sel = (k1 == r["k1"]) & (k2 == r["k2"])
        np.testing.assert_allclose(r["v"], v[sel].max(axis=0), rtol=1e-6)


def test_daggregate_pad_rows_excluded(mesh8):
    # 10 rows pad to 16 on an 8-shard mesh; pad rows must not contribute
    df = tft.frame({"key": np.zeros(10, np.int64),
                    "x": np.ones(10)})
    dist = par.distribute(df, mesh8)
    assert dist.padded_rows == 16
    out = par.daggregate({"x": "sum"}, dist, "key")
    rows = out.collect()
    assert len(rows) == 1 and rows[0]["x"] == 10.0


def test_daggregate_unused_value_column_ignored(mesh8):
    # ride-along tolerance (the reduce contract,
    # BasicOperationsSuite.scala:178-187): `extra` drops out of the result
    df = tft.frame({"key": np.zeros(4, np.int64), "x": np.arange(4.0),
                    "extra": np.arange(4.0)})
    dist = par.distribute(df, mesh8)
    out = par.daggregate({"x": "sum"}, dist, "key")
    rows = out.collect()
    assert len(rows) == 1 and rows[0]["x"] == pytest.approx(6.0)
    assert "extra" not in [n for n in out.schema.names]


def test_daggregate_generic_computation_matches_host(mesh8):
    # An arbitrary (non-monoid) algebraic reduce — the UDAF-inside-the-
    # shuffle contract (reference DebugRowOps.scala:587-681) on the mesh:
    # L2-norm accumulation over scalar and vector columns.
    import jax.numpy as jnp
    from tensorframes_tpu.engine import ops as engine_ops

    rng = np.random.default_rng(21)
    n = 500
    key = rng.integers(0, 13, n).astype(np.int64)
    v = rng.normal(size=n)
    w = rng.normal(size=(n, 3))
    df = tft.analyze(tft.frame({"k": key, "v": v, "w": w},
                               num_partitions=4))

    def fetch(v_input, w_input):
        return {"v": jnp.sqrt((v_input ** 2).sum(0)),
                "w": jnp.sqrt((w_input ** 2).sum(0))}

    host = engine_ops.aggregate(fetch, df.group_by("k"))
    dist = par.distribute(df, mesh8)
    out = par.daggregate(fetch, dist, "k")
    h = {r["k"]: (r["v"], r["w"]) for r in host.collect()}
    m = {r["k"]: (r["v"], r["w"]) for r in out.collect()}
    assert set(h) == set(m)
    for k in h:
        np.testing.assert_allclose(h[k][0], m[k][0], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(h[k][1]),
                                   np.asarray(m[k][1]), rtol=1e-6)


def test_daggregate_generic_single_row_groups(mesh8):
    # Single-row groups must still see one application of the computation
    # (host CompactionBuffer.evaluate always applies it): sqrt(x^2) = |x|
    # distinguishes "raw row passed through" from "computation applied".
    import jax.numpy as jnp
    from tensorframes_tpu.engine import ops as engine_ops

    key = np.arange(10, dtype=np.int64)     # every group has exactly 1 row
    x = np.linspace(-5, 4, 10)

    def fetch(x_input):
        return {"x": jnp.sqrt((x_input ** 2).sum(0))}

    df = tft.frame({"k": key, "x": x})
    host = engine_ops.aggregate(fetch, df.group_by("k"))
    dist = par.distribute(df, mesh8)
    out = par.daggregate(fetch, dist, "k")
    h = {r["k"]: r["x"] for r in host.collect()}
    m = {r["k"]: r["x"] for r in out.collect()}
    assert h == pytest.approx(m)
    assert m[0] == pytest.approx(5.0)  # |−5|, not −5


def test_daggregate_generic_multi_key_pad_rows(mesh8):
    import jax.numpy as jnp
    from tensorframes_tpu.engine import ops as engine_ops

    rng = np.random.default_rng(22)
    n = 30                                   # pads to 32 on 8 shards
    k1 = rng.integers(0, 3, n).astype(np.int64)
    k2 = rng.integers(0, 2, n).astype(np.int64)
    x = rng.normal(size=n)

    def fetch(x_input):
        return {"x": jnp.sqrt((x_input ** 2).sum(0))}

    df = tft.frame({"k1": k1, "k2": k2, "x": x})
    host = engine_ops.aggregate(fetch, df.group_by("k1", "k2"))
    dist = par.distribute(df, mesh8)
    assert dist.padded_rows == 32
    out = par.daggregate(fetch, dist, ["k1", "k2"])
    h = {(r["k1"], r["k2"]): r["x"] for r in host.collect()}
    m = {(r["k1"], r["k2"]): r["x"] for r in out.collect()}
    assert set(h) == set(m)
    for k in h:
        np.testing.assert_allclose(h[k], m[k], rtol=1e-6)


def test_daggregate_device_keys_matches_host_path(mesh8):
    rng = np.random.default_rng(31)
    n = 400
    key = rng.integers(0, 37, n).astype(np.int64)
    x = rng.normal(size=n)
    v = rng.normal(size=(n, 2))
    df = tft.frame({"k": key, "x": x, "v": v})
    dist = par.distribute(df, mesh8)
    host_out = par.daggregate({"x": "sum", "v": "max"}, dist, "k")
    dev_out = par.daggregate({"x": "sum", "v": "max"}, dist, "k",
                             max_groups=64)
    h = {r["k"]: (r["x"], r["v"]) for r in host_out.collect()}
    d = {r["k"]: (r["x"], r["v"]) for r in dev_out.collect()}
    assert set(h) == set(d)
    for k in h:
        np.testing.assert_allclose(h[k][0], d[k][0], rtol=1e-9)
        np.testing.assert_allclose(np.asarray(h[k][1]),
                                   np.asarray(d[k][1]), rtol=1e-9)


def test_daggregate_device_keys_cap_overflow_raises(mesh8):
    df = tft.frame({"k": np.arange(20, dtype=np.int64),
                    "x": np.ones(20)})
    dist = par.distribute(df, mesh8)
    with pytest.raises(ValueError, match="max_groups"):
        par.daggregate({"x": "sum"}, dist, "k", max_groups=10)


def test_daggregate_device_keys_pad_rows_excluded(mesh8):
    # 10 rows pad to 16; pad rows must not form a phantom group
    df = tft.frame({"k": np.zeros(10, np.int64), "x": np.ones(10)})
    dist = par.distribute(df, mesh8)
    out = par.daggregate({"x": "sum"}, dist, "k", max_groups=4)
    rows = out.collect()
    assert len(rows) == 1 and rows[0]["x"] == 10.0 and rows[0]["k"] == 0


def test_daggregate_generic_device_keys(mesh8):
    import jax.numpy as jnp

    rng = np.random.default_rng(41)
    n = 300
    key = rng.integers(0, 19, n).astype(np.int32)
    x = rng.normal(size=n)
    df = tft.frame({"k": key, "x": x})
    dist = par.distribute(df, mesh8)

    def fetch(x_input):
        return {"x": jnp.sqrt((x_input ** 2).sum(0))}

    host_out = par.daggregate(fetch, dist, "k")
    dev_out = par.daggregate(fetch, dist, "k", max_groups=32)
    h = {r["k"]: r["x"] for r in host_out.collect()}
    d = {r["k"]: r["x"] for r in dev_out.collect()}
    assert set(h) == set(d)
    for k in h:
        np.testing.assert_allclose(h[k], d[k], rtol=1e-6)


def test_daggregate_device_keys_narrowed_long_rejected(mesh8):
    # int64 keys narrowed to int32 on device (x64 off in this test? the
    # conftest enables x64, so simulate via an int column that is exact) —
    # assert the guard exists by checking the host-path error parity: when
    # the device dtype is narrower than storage, both paths must refuse.
    from tensorframes_tpu.engine.ops import InvalidTypeError
    import jax

    if not jax.config.jax_enable_x64:
        df = tft.frame({"k": np.array([1, 1 + 2**32] * 8, np.int64),
                        "x": np.ones(16)})
        dist = par.distribute(df, mesh8)
        with pytest.raises(InvalidTypeError, match="narrowed"):
            par.daggregate({"x": "sum"}, dist, "k", max_groups=4)
    else:
        # x64 on (CPU tests): no narrowing occurs; both paths agree
        df = tft.frame({"k": np.array([1, 1 + 2**32] * 8, np.int64),
                        "x": np.ones(16)})
        dist = par.distribute(df, mesh8)
        out = par.daggregate({"x": "sum"}, dist, "k", max_groups=4)
        assert len(out.collect()) == 2


def test_distribute_string_key_column_rides_host_side(mesh8):
    # geom_mean-style pipeline: string group keys alongside tensor values
    # (reference carried non-numeric Catalyst columns through untouched);
    # the key column stays host-side, values shard.
    df = tft.frame([(str(i % 3), float(i)) for i in range(10)],
                   columns=["key", "x"])
    dist = par.distribute(df, mesh8)
    out = par.daggregate({"x": "sum"}, dist, "key")
    got = {r["key"]: r["x"] for r in out.collect()}
    want = {}
    for i in range(10):
        want[str(i % 3)] = want.get(str(i % 3), 0.0) + float(i)
    assert got == pytest.approx(want)
    # round trip preserves the string column
    back = par.dmap_blocks(lambda x: {"z": x + 1.0}, dist).collect_frame()
    rows = back.collect()
    assert sorted((r["key"], r["x"], r["z"]) for r in rows) == sorted(
        (str(i % 3), float(i), float(i) + 1.0) for i in range(10))


def test_daggregate_key_factorization_cached(mesh8, monkeypatch):
    # repeated aggregations over the same keys on the same frame must not
    # re-run the host transfer + factorization (or the device sort-unique
    # program): the frame memoizes per key tuple
    from tensorframes_tpu.parallel import distributed as dmod

    rng = np.random.default_rng(13)
    keys = rng.integers(0, 20, 200)
    vals = rng.normal(size=200)
    df = tft.frame({"key": keys, "x": vals})
    dist = par.distribute(df, mesh8)

    calls = {"host": 0, "device": 0}
    orig_host, orig_dev = dmod._host_group_ids, dmod._device_key_ids

    def count_host(*a, **k):
        calls["host"] += 1
        return orig_host(*a, **k)

    def count_dev(*a, **k):
        calls["device"] += 1
        return orig_dev(*a, **k)

    monkeypatch.setattr(dmod, "_host_group_ids", count_host)
    monkeypatch.setattr(dmod, "_device_key_ids", count_dev)

    first = par.daggregate({"x": "sum"}, dist, "key")
    again = par.daggregate({"x": "min"}, dist, "key")   # same keys, new fetch
    gen = par.daggregate(lambda x_input: {"x": x_input.sum(0)}, dist, "key")
    assert calls["host"] == 1

    dev1 = par.daggregate({"x": "sum"}, dist, "key", max_groups=32)
    dev2 = par.daggregate({"x": "max"}, dist, "key", max_groups=32)
    assert calls["device"] == 1
    # a different cap is a different static program: fresh entry
    par.daggregate({"x": "sum"}, dist, "key", max_groups=64)
    assert calls["device"] == 2

    # and the cached ids still produce correct results
    ref = {}
    for k, v in zip(keys, vals):
        ref[int(k)] = ref.get(int(k), 0.0) + v
    for out in (first, dev1):
        got = {int(r["key"]): float(r["x"]) for r in out.collect()}
        for k in ref:
            assert np.isclose(got[k], ref[k], rtol=1e-9)
    gmin = {int(r["key"]): float(r["x"]) for r in again.collect()}
    for k in ref:
        assert np.isclose(gmin[k], vals[keys == k].min(), rtol=1e-9)
    gsum = {int(r["key"]): float(r["x"]) for r in gen.collect()}
    for k in ref:
        assert np.isclose(gsum[k], ref[k], rtol=1e-6)


class TestDFilter:
    def test_matches_host_filter(self, mesh8):
        rng = np.random.default_rng(21)
        x = rng.normal(size=1000)
        v = rng.normal(size=(1000, 3))
        df = tft.analyze(tft.frame({"x": x, "v": v}))
        dist = par.distribute(df, mesh8)
        out = par.dfilter(lambda x: x > 0.0, dist)
        assert out.count() == int((x > 0).sum())
        back = out.collect_frame().collect()
        keep = x > 0
        # per-shard compaction does not reorder within a shard, but shard
        # boundaries differ from host partitioning: compare as sets
        got = sorted((r["x"], tuple(r["v"])) for r in back)
        want = sorted(zip(x[keep], map(tuple, v[keep])))
        for (gx, gv), (wx, wv) in zip(got, want):
            assert gx == pytest.approx(wx, rel=1e-6)
            np.testing.assert_allclose(gv, wv, rtol=1e-6)

    def test_chains_with_dmap_and_dreduce(self, mesh8):
        x = np.arange(100, dtype=np.float64)
        dist = par.distribute(tft.frame({"x": x}), mesh8)
        flt = par.dfilter(lambda x: x >= 50.0, dist)
        mapped = par.dmap_blocks(lambda x: {"z": x * 2.0}, flt)
        total = par.dreduce_blocks({"z": "sum"}, mapped.select(["z"]))
        assert float(total["z"]) == float((x[x >= 50] * 2).sum())

    def test_pad_rows_never_survive(self, mesh8):
        # 10 rows pad to 16 on 8 shards; an always-true predicate must
        # still drop the 6 pad rows
        dist = par.distribute(tft.frame({"x": np.ones(10)}), mesh8)
        out = par.dfilter(lambda x: x > 0.0, dist)
        assert out.count() == 10
        assert len(out.collect_frame().collect()) == 10

    def test_string_rider_column_permutes(self, mesh8):
        keys = np.array([f"k{i}" for i in range(12)], object)
        x = np.arange(12, dtype=np.float64)
        df = tft.frame({"k": keys, "x": x})
        dist = par.distribute(df, mesh8)
        out = par.dfilter(lambda x: x % 2.0 == 0.0, dist)
        rows = out.collect_frame().collect()
        assert sorted((r["k"], r["x"]) for r in rows) == sorted(
            (f"k{i}", float(i)) for i in range(0, 12, 2))

    def test_filter_all_gone_then_count_zero(self, mesh8):
        dist = par.distribute(tft.frame({"x": np.ones(16)}), mesh8)
        out = par.dfilter(lambda x: x < 0.0, dist)
        assert out.count() == 0

    def test_host_column_predicate_typed_error(self, mesh8):
        # a predicate selecting a string (host-side) column must raise a
        # typed error, not a bare KeyError from inside shard_map tracing
        from tensorframes_tpu import dtypes as _dt
        from tensorframes_tpu.computation import Computation, TensorSpec
        from tensorframes_tpu.engine.ops import InvalidTypeError
        from tensorframes_tpu.shape import Shape, Unknown

        k = np.array(["a", "b"], object)
        dist = par.distribute(tft.frame({"k": k, "x": np.arange(2.0)}),
                              mesh8)
        # lambda path: rejected at trace time by the computation builder
        with pytest.raises(InvalidTypeError, match="non-tensor"):
            par.dfilter(lambda k: (k != 0).astype(np.int32), dist)
        # pre-built Computation path (trace bypassed): dfilter's own guard
        comp = Computation.trace(
            lambda k: {"keep": (k > 0).astype(np.int32)},
            [TensorSpec("k", _dt.double, Shape(Unknown))])
        with pytest.raises(InvalidTypeError, match="host-side"):
            par.dfilter(comp, dist)

    def test_dfilter_reuses_compiled_program(self, mesh8):
        # the predicate's Computation (and so its shard_map jit cache)
        # must be reused across calls — a fresh trace per call would pay
        # full XLA compile every iteration of a driver loop
        from tensorframes_tpu.engine import ops as eops

        pred = lambda x: x > 0.0  # noqa: E731
        dist = par.distribute(tft.frame({"x": np.arange(16.0)}), mesh8)
        par.dfilter(pred, dist)
        comp = eops.cached_map_computation(pred, dist.schema,
                                           block_level=True)
        assert comp._tft_dfilter_cache  # populated by the first call
        before = dict(comp._tft_dfilter_cache)
        out = par.dfilter(pred, dist)
        assert comp._tft_dfilter_cache == before  # same compiled entry
        assert out.count() == 15


class TestDSort:
    def test_matches_host_order_by(self, mesh8):
        rng = np.random.default_rng(31)
        x = rng.normal(size=100)
        v = rng.normal(size=(100, 2))
        df = tft.analyze(tft.frame({"x": x, "v": v}))
        dist = par.distribute(df, mesh8)
        out = par.dsort("x", dist)
        rows = out.collect_frame().collect()
        order = np.argsort(x, stable=True)
        np.testing.assert_allclose([r["x"] for r in rows], x[order],
                                   rtol=1e-7)
        np.testing.assert_allclose(np.stack([r["v"] for r in rows]),
                                   v[order], rtol=1e-7)

    def test_descending_and_multi_key(self, mesh8):
        k = np.array([1, 0, 1, 0, 2, 2], np.int64)
        x = np.array([6.0, 5.0, 4.0, 3.0, 2.0, 1.0])
        dist = par.distribute(tft.frame({"k": k, "x": x}), mesh8)
        rows = par.dsort(["k", "x"], dist).collect_frame().collect()
        assert [(r["k"], r["x"]) for r in rows] == [
            (0, 3.0), (0, 5.0), (1, 4.0), (1, 6.0), (2, 1.0), (2, 2.0)]
        rows = par.dsort("x", dist, descending=True) \
            .collect_frame().collect()
        assert [r["x"] for r in rows] == [6.0, 5.0, 4.0, 3.0, 2.0, 1.0]

    def test_pad_rows_sink_and_normalize_mask_layout(self, mesh8):
        # dfilter leaves a per-shard mask layout; dsort must sort only the
        # real rows and emerge with prefix validity
        x = np.arange(20, dtype=np.float64)
        dist = par.distribute(tft.frame({"x": x}), mesh8)
        flt = par.dfilter(lambda x: x % 3.0 == 0.0, dist)
        out = par.dsort("x", flt, descending=True)
        assert out.shard_valid is None  # prefix layout restored
        rows = out.collect_frame().collect()
        assert [r["x"] for r in rows] == [18.0, 15.0, 12.0, 9.0, 6.0,
                                          3.0, 0.0]

    def test_string_rider_follows(self, mesh8):
        k = np.array([f"s{i}" for i in range(10)], object)
        x = np.arange(10, dtype=np.float64)[::-1].copy()
        dist = par.distribute(tft.frame({"k": k, "x": x}), mesh8)
        rows = par.dsort("x", dist).collect_frame().collect()
        assert [r["k"] for r in rows] == [f"s{i}" for i in range(9, -1, -1)]

    def test_string_key_rejected(self, mesh8):
        from tensorframes_tpu.engine.ops import InvalidTypeError

        k = np.array(["a", "b"], object)
        dist = par.distribute(tft.frame({"k": k, "x": np.arange(2.0)}),
                              mesh8)
        with pytest.raises(InvalidTypeError, match="host-side"):
            par.dsort("k", dist)

    def test_nan_keys_stay_in_valid_prefix(self, mesh8):
        # a real row keyed NaN must not be displaced into the pad region
        # (10 rows pad to 16): it sorts last among the REAL rows
        x = np.array([3.0, np.nan, 1.0, 4.0, 0.5, 2.0, 9.0, 8.0, 7.0,
                      6.0])
        dist = par.distribute(tft.frame({"x": x}), mesh8)
        rows = par.dsort("x", dist).collect_frame().collect()
        got = [r["x"] for r in rows]
        assert len(got) == 10
        assert np.isnan(got[-1])
        assert got[:-1] == sorted(v for v in x if not np.isnan(v))

    def test_descending_unsigned_and_int_min(self, mesh8):
        # raw negation wraps uint 0 onto itself and overflows iinfo.min;
        # the bitwise-not transform must order both correctly
        u = np.array([5, 0, 7, 255], np.uint8)
        dist = par.distribute(tft.frame({"u": u, "x": np.arange(4.0)}),
                              mesh8)
        rows = par.dsort("u", dist, descending=True) \
            .collect_frame().collect()
        assert [r["u"] for r in rows] == [255, 7, 5, 0]
        i = np.array([5, np.iinfo(np.int32).min, -1, 3], np.int64)
        dist = par.distribute(tft.frame({"i": i, "x": np.arange(4.0)}),
                              mesh8)
        rows = par.dsort("i", dist, descending=True) \
            .collect_frame().collect()
        assert [r["i"] for r in rows] == [5, 3, -1, np.iinfo(np.int32).min]


class TestHostMeshConformance:
    """Randomized cross-check: every mesh op must agree with its host
    twin on the same data (the ExtractNodes two-lowerings pattern applied
    to the distribution layer)."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_frames_agree(self, mesh8, seed):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(30, 200))
        g = int(rng.integers(2, 12))
        df = tft.analyze(tft.frame({
            "k": rng.integers(0, g, n).astype(np.int32),
            "x": rng.normal(size=n),
            "v": rng.normal(size=(n, int(rng.integers(1, 4)))),
        }, num_partitions=int(rng.integers(1, 5))))
        dist = par.distribute(df, mesh8)

        # map
        h = tft.map_blocks(lambda x, v: {"z": x[:, None] * v}, df)
        m = par.dmap_blocks(lambda x, v: {"z": x[:, None] * v}, dist)
        hz = np.concatenate([b.dense("z") for b in h.blocks()])
        mz = np.concatenate(
            [b.dense("z") for b in m.collect_frame().blocks()])
        np.testing.assert_allclose(mz, hz, rtol=1e-6)

        # reduce (monoid + generic)
        hs = tft.reduce_blocks(lambda x_input: {"x": x_input.sum(0)},
                               df.select(["x"]))
        ms = par.dreduce_blocks({"x": "sum"}, dist.select(["x"]))
        np.testing.assert_allclose(ms["x"], hs, rtol=1e-6)
        hm = tft.reduce_blocks(
            lambda v_input: {"v": jnp.max(v_input, axis=0)},
            df.select(["v"]))
        mm = par.dreduce_blocks(
            lambda v_input: {"v": jnp.max(v_input, axis=0)},
            dist.select(["v"]))
        np.testing.assert_allclose(mm["v"], hm, rtol=1e-6)

        # aggregate
        ha = tft.aggregate({"x": "sum"}, df.select(["k", "x"])
                           .group_by("k")).collect()
        ma = par.daggregate({"x": "sum"}, dist.select(["k", "x"]),
                            "k").collect()
        hd = {r["k"]: r["x"] for r in ha}
        md = {r["k"]: r["x"] for r in ma}
        assert set(hd) == set(md)
        for kk in hd:
            np.testing.assert_allclose(md[kk], hd[kk], rtol=1e-6)

        # filter + sort chain
        hf = df.filter(lambda x: x > 0.0).order_by("x").collect()
        mf = par.dsort("x", par.dfilter(lambda x: x > 0.0, dist)) \
            .collect_frame().collect()
        np.testing.assert_allclose([r["x"] for r in mf],
                                   [r["x"] for r in hf], rtol=1e-7)


def test_distributed_frame_explain(mesh8):
    k = np.array(["a", "b"], object)
    df = tft.analyze(tft.frame({"k": k, "x": np.arange(2.0),
                                "v": np.ones((2, 3))}))
    dist = par.distribute(df, mesh8)
    out = dist.explain()
    assert "2 rows" in out and "padded 8" in out
    assert "prefix" in out
    assert "host (ride-along)" in out            # string column
    assert "x: double" in out and "v: array<double>" in out
    assert "PartitionSpec('data'" in out
    flt = par.dfilter(lambda x: x >= 0.0, dist)
    assert "per-shard" in flt.explain()


class TestColumnsort:
    """Stress the multi-shard columnsort path specifically (8 shards:
    every run exercises deal/undeal all_to_alls, the half-block shift,
    and the internal sentinel padding, since 2(S-1)^2 = 98 > most test
    frames' rows-per-shard)."""

    def test_randomized_against_numpy(self, mesh8):
        rng = np.random.default_rng(1234)
        for n in (16, 97, 800, 4096):
            x = rng.normal(size=n)
            dist = par.distribute(tft.frame({"x": x}), mesh8)
            rows = par.dsort("x", dist).collect_frame().collect()
            np.testing.assert_allclose(
                [r["x"] for r in rows], np.sort(x), rtol=0)

    def test_randomized_multikey_stability(self, mesh8):
        rng = np.random.default_rng(5)
        n = 1000
        k1 = rng.integers(0, 7, n)
        k2 = rng.integers(0, 5, n).astype(np.float64)
        tag = np.arange(n, dtype=np.float64)  # original position
        dist = par.distribute(
            tft.frame({"k1": k1, "k2": k2, "tag": tag}), mesh8)
        rows = par.dsort(["k1", "k2"], dist).collect_frame().collect()
        got = [(r["k1"], r["k2"], r["tag"]) for r in rows]
        order = np.lexsort((tag, k2, k1))  # lexsort: last key primary
        want = [(k1[i], k2[i], tag[i]) for i in order]
        assert got == want  # exact, including stable tie order

    def test_randomized_descending_ints(self, mesh8):
        rng = np.random.default_rng(6)
        v = rng.integers(np.iinfo(np.int64).min,
                         np.iinfo(np.int64).max, 700, dtype=np.int64)
        dist = par.distribute(
            tft.frame({"v": v, "x": np.zeros(700)}), mesh8)
        rows = par.dsort("v", dist, descending=True) \
            .collect_frame().collect()
        assert [r["v"] for r in rows] == sorted(v.tolist(), reverse=True)

    def test_after_dfilter_mask_layout(self, mesh8):
        # dfilter leaves per-shard validity; columnsort must sink exactly
        # the invalid rows, restoring prefix layout
        rng = np.random.default_rng(7)
        x = rng.normal(size=500)
        dist = par.distribute(tft.frame({"x": x}), mesh8)
        flt = par.dfilter(lambda x: x > 0.0, dist)
        out = par.dsort("x", flt, descending=True)
        assert out.shard_valid is None
        rows = out.collect_frame().collect()
        want = sorted((v for v in x if v > 0), reverse=True)
        np.testing.assert_allclose([r["x"] for r in rows], want, rtol=0)

    def test_gather_fallback_warns_once(self, mesh8, caplog, monkeypatch):
        # a multi-shard frame whose rows do NOT tile the data axis takes
        # the local-argsort program, whose GSPMD lowering gathers the key
        # column — that silent return must warn (once), VERDICT r4 #4a
        import logging

        from tensorframes_tpu.parallel import distributed as _dist

        monkeypatch.setattr(_dist, "_dsort_gather_warned", False)
        x = np.arange(48.0)
        dist = par.distribute(tft.frame({"x": x}), mesh8)
        # trim/global map: 6 output rows on an 8-shard mesh
        summary = par.dmap_blocks(
            lambda x: {"s": -x[:6]}, dist, trim=True, row_aligned=False)
        assert summary.padded_rows % mesh8.num_data_shards != 0
        with caplog.at_level(logging.WARNING,
                             logger="tensorframes_tpu.dsort"):
            out = par.dsort("s", summary)
            rows = out.collect_frame().collect()
        assert [r["s"] for r in rows] == sorted((-x[:6]).tolist())
        gather_warnings = [r for r in caplog.records
                           if "gather" in r.message]
        assert len(gather_warnings) == 1
        # second call: warned once per process, no repeat
        with caplog.at_level(logging.WARNING,
                             logger="tensorframes_tpu.dsort"):
            par.dsort("s", summary, descending=True)
        assert len([r for r in caplog.records
                    if "gather" in r.message]) == 1

    def test_vector_and_string_riders(self, mesh8):
        rng = np.random.default_rng(8)
        n = 300
        x = rng.permutation(n).astype(np.float64)
        v = np.stack([x * 2, x * 3], axis=1)
        s = np.array([f"s{int(i)}" for i in x], object)
        df = tft.analyze(tft.frame({"x": x, "v": v, "s": s}))
        dist = par.distribute(df, mesh8)
        rows = par.dsort("x", dist).collect_frame().collect()
        for i, r in enumerate(rows):
            assert r["x"] == float(i)
            np.testing.assert_allclose(r["v"], [i * 2.0, i * 3.0])
            assert r["s"] == f"s{i}"


def test_group_ids_cache_lru_capped(mesh8):
    # the per-frame factorization memo holds device arrays sized like the
    # frame; sweeping one long-lived frame over many max_groups caps must
    # not retain them all (ADVICE r3: cap it like _dsort_cache)
    from tensorframes_tpu.parallel.distributed import (
        _GROUP_IDS_CACHE_CAP, _cached_group_ids)

    k = np.arange(64, dtype=np.int32) % 4
    dist = par.distribute(tft.frame({"k": k, "x": np.ones(64)}), mesh8)
    for cap in range(4, 4 + _GROUP_IDS_CACHE_CAP + 4):
        _cached_group_ids(dist, ["k"], cap)
    assert len(dist._group_ids_cache) == _GROUP_IDS_CACHE_CAP
    # freshest entry survives and is reused (LRU, not clear-all)
    newest = ("device", ("k",), 4 + _GROUP_IDS_CACHE_CAP + 3)
    assert newest in dist._group_ids_cache


class TestDeviceKeysMultiKey:
    def test_two_key_monoid_matches_host_path(self, mesh8):
        rng = np.random.default_rng(41)
        n = 3000
        k1 = rng.integers(-5, 5, n).astype(np.int32)   # negatives too
        k2 = rng.integers(0, 7, n).astype(np.int32)
        x = rng.normal(size=n)
        df = tft.frame({"k1": k1, "k2": k2, "x": x})
        dist = par.distribute(df, mesh8)
        host = par.daggregate({"x": "sum"}, dist, ["k1", "k2"])
        dev = par.daggregate({"x": "sum"}, dist, ["k1", "k2"],
                             max_groups=128)
        h = {(r["k1"], r["k2"]): r["x"] for r in host.collect()}
        d = {(r["k1"], r["k2"]): r["x"] for r in dev.collect()}
        assert set(h) == set(d) and len(d) == len(
            {(a, b) for a, b in zip(k1, k2)})
        for kk in h:
            np.testing.assert_allclose(d[kk], h[kk], rtol=1e-9)

    def test_two_key_generic_matches_host_path(self, mesh8):
        rng = np.random.default_rng(42)
        n = 500
        k1 = rng.integers(0, 4, n).astype(np.int32)
        k2 = rng.integers(0, 3, n).astype(np.int32)
        v = rng.normal(size=(n, 2))
        dist = par.distribute(tft.frame({"k1": k1, "k2": k2, "v": v}),
                              mesh8)
        host = par.daggregate(
            lambda v_input: {"v": jnp.sqrt((v_input ** 2).sum(0))},
            dist, ["k1", "k2"])
        dev = par.daggregate(
            lambda v_input: {"v": jnp.sqrt((v_input ** 2).sum(0))},
            dist, ["k1", "k2"], max_groups=32)
        h = {(r["k1"], r["k2"]): r["v"] for r in host.collect()}
        d = {(r["k1"], r["k2"]): r["v"] for r in dev.collect()}
        assert set(h) == set(d)
        for kk in h:
            np.testing.assert_allclose(d[kk], h[kk], rtol=1e-6)

    def test_three_keys(self, mesh8):
        rng = np.random.default_rng(43)
        n = 200
        cols = {f"k{i}": rng.integers(0, 3, n).astype(np.int32)
                for i in range(3)}
        cols["x"] = rng.normal(size=n)
        dist = par.distribute(tft.frame(cols), mesh8)
        dev = par.daggregate({"x": "max"}, dist, ["k0", "k1", "k2"],
                             max_groups=27).collect()
        for r in dev:
            sel = ((cols["k0"] == r["k0"]) & (cols["k1"] == r["k1"])
                   & (cols["k2"] == r["k2"]))
            np.testing.assert_allclose(r["x"], cols["x"][sel].max(),
                                       rtol=1e-9)

    def test_cap_overflow_errors(self, mesh8):
        n = 100
        k1 = np.arange(n, dtype=np.int32)      # 100 distinct
        k2 = np.zeros(n, np.int32)
        dist = par.distribute(tft.frame({"k1": k1, "k2": k2,
                                         "x": np.ones(n)}), mesh8)
        with pytest.raises(ValueError, match="distinct"):
            par.daggregate({"x": "sum"}, dist, ["k1", "k2"],
                           max_groups=10)
        with pytest.raises(ValueError, match="int32 combined-id"):
            par.daggregate({"x": "sum"}, dist, ["k1", "k2"],
                           max_groups=100_000)


def test_daggregate_empty_keys_rejected(mesh8):
    dist = par.distribute(tft.frame({"x": np.ones(8)}), mesh8)
    with pytest.raises(ValueError, match="at least one key"):
        par.daggregate({"x": "sum"}, dist, [])
    with pytest.raises(ValueError, match="at least one key"):
        par.daggregate({"x": "sum"}, dist, [], max_groups=4)
