"""Computation IR tests: tracing, shape inference, serialization,
analyze_graph validation (the TFInitializationSuite/analyzeGraph analogue)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.computation import (
    Computation, TensorSpec, analyze_graph)
from tensorframes_tpu.shape import Shape, Unknown


def specs(**kw):
    return [TensorSpec(n, d, s) for n, (d, s) in kw.items()]


def test_trace_simple_add():
    c = Computation.trace(
        lambda x: {"z": x + 3.0},
        specs(x=(dt.double, Shape(Unknown))))
    assert c.input_names == ["x"]
    assert c.output_names == ["z"]
    assert c.output("z").shape == Shape(Unknown)
    out = c({"x": jnp.asarray(np.arange(4.0))})
    np.testing.assert_allclose(np.asarray(out["z"]), np.arange(4.0) + 3)


def test_outputs_sorted_by_name():
    c = Computation.trace(
        lambda x: {"b": x, "a": x * 2},
        specs(x=(dt.double, Shape(Unknown))))
    assert c.output_names == ["a", "b"]


def test_shared_lead_dim_across_inputs():
    c = Computation.trace(
        lambda x, y: {"z": x + y},
        specs(x=(dt.double, Shape(Unknown)), y=(dt.double, Shape(Unknown))))
    assert c.output("z").shape == Shape(Unknown)


def test_block_reduce_shape():
    c = Computation.trace(
        lambda x_input: {"x": jnp.sum(x_input, axis=0)},
        specs(x_input=(dt.double, Shape(Unknown, 3))))
    assert c.output("x").shape == Shape(3)


def test_single_output_named_after_function():
    def doubled(x):
        return x * 2
    c = Computation.trace(doubled, specs(x=(dt.double, Shape(Unknown))))
    assert c.output_names == ["doubled"]


def test_trace_dict_style_fn():
    def f(cols):
        return {"z": cols["x"] + cols["y"]}
    c = Computation.trace(
        f, specs(x=(dt.double, Shape(Unknown)), y=(dt.double, Shape(Unknown))))
    assert c.output_names == ["z"]


def test_missing_input_raises():
    c = Computation.trace(
        lambda x: {"z": x}, specs(x=(dt.double, Shape(Unknown))))
    with pytest.raises(ValueError, match="Missing computation inputs"):
        c({})


def test_serialize_roundtrip():
    c = Computation.trace(
        lambda x: {"z": x * 2 + 1, "m": jnp.min(x, axis=0)},
        specs(x=(dt.double, Shape(Unknown, 2))))
    blob = c.serialize()
    c2 = Computation.deserialize(blob)
    assert c2.input_names == ["x"]
    assert c2.output_names == ["m", "z"]
    assert c2.output("z").shape == Shape(Unknown, 2)
    x = np.arange(8.0).reshape(4, 2)
    out = c2({"x": x})
    np.testing.assert_allclose(np.asarray(out["z"]), x * 2 + 1)
    np.testing.assert_allclose(np.asarray(out["m"]), x.min(axis=0))


def test_deserialize_rejects_garbage():
    with pytest.raises(ValueError, match="Not a serialized"):
        Computation.deserialize(b"not-a-computation")


def test_analyze_graph_summaries():
    c = Computation.trace(
        lambda x: {"z": x + 1.0},
        specs(x=(dt.double, Shape(Unknown))))
    summ = analyze_graph(c)
    assert [s.name for s in summ] == ["x", "z"]
    assert summ[0].is_input and not summ[0].is_output
    assert summ[1].is_output


def test_analyze_graph_hint_refines():
    c = Computation.trace(
        lambda x: {"z": x}, specs(x=(dt.double, Shape(Unknown))))
    summ = analyze_graph(c, shape_hints={"x": Shape(10)})
    assert summ[0].shape == Shape(10)


def test_analyze_graph_bad_hint_and_fetch():
    c = Computation.trace(
        lambda x: {"z": x}, specs(x=(dt.double, Shape(Unknown, 3))))
    with pytest.raises(ValueError, match="incompatible"):
        analyze_graph(c, shape_hints={"x": Shape(Unknown)})
    with pytest.raises(ValueError, match="not produced"):
        analyze_graph(c, fetches=["nope"])


def test_fallback_inference_for_symbolic_hostile_ops():
    # jnp.reshape(x, (-1,)) handles symbolic fine, but argsort-based tricks
    # may not; exercise the sentinel fallback via an op that inspects shape.
    def f(x):
        n = x.shape[0]
        return {"z": jnp.broadcast_to(jnp.sum(x), (n,))}
    c = Computation.trace(f, specs(x=(dt.double, Shape(Unknown))))
    assert c.output("z").shape == Shape(Unknown)
