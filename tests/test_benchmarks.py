"""The perf harness must stay runnable (the reference's suites rotted to
``ignore``; ours are exercised at light scale in CI). Heavy runs are
opt-in: ``python -m benchmarks.run_all``."""

import json
import subprocess
import sys

import pytest

from benchmarks import baseline_configs, e2e_bench, marshal_bench


def test_marshal_bench_light():
    recs = marshal_bench.run(n_scalar=20_000, n_vector=20_000, iters=1)
    metrics = {r["metric"] for r in recs}
    assert metrics == {"convert_scalar_rows", "convertBack_scalar_rows",
                       "convert_1row_vector", "convertBack_1row_vector"}
    assert all(r["value"] > 0 for r in recs)


def test_e2e_bench_light():
    recs = e2e_bench.run(n_rows=50_000, iters=1)
    assert {r["metric"] for r in recs} == {"e2e_map_agg_host",
                                           "e2e_map_agg_device"}


def test_baseline_light_configs():
    recs = baseline_configs.run(heavy=False)
    assert {r["metric"] for r in recs} == {
        "readme_x_plus_3", "reduce_sum_min_vector", "dsl_map_blocks_1m"}


@pytest.mark.slow
def test_heavy_configs_smoke():
    r4 = baseline_configs.config4_resnet_inference(batch=2, image=64,
                                                   iters=1)
    assert r4["images_per_s"] > 0
    r5 = baseline_configs.config5_logreg_step(n=4096, d=8)
    assert r5["rows_per_s"] > 0
