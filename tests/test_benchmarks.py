"""The perf harness must stay runnable (the reference's suites rotted to
``ignore``; ours are exercised at light scale in CI). Heavy runs are
opt-in: ``python -m benchmarks.run_all``."""

import json
import os
import subprocess
import sys

import pytest

from benchmarks import baseline_configs, e2e_bench, marshal_bench


def test_marshal_bench_light():
    recs = marshal_bench.run(n_scalar=20_000, n_vector=20_000, iters=1)
    metrics = {r["metric"] for r in recs}
    assert metrics == {"convert_scalar_rows", "convertBack_scalar_rows",
                       "convert_1row_vector", "convertBack_1row_vector"}
    assert all(r["value"] > 0 for r in recs)


def test_e2e_bench_light():
    recs = e2e_bench.run(n_rows=50_000, iters=1)
    assert {r["metric"] for r in recs} == {"e2e_map_agg_host",
                                           "e2e_map_agg_device"}


def test_baseline_light_configs():
    recs = baseline_configs.run(heavy=False)
    assert {r["metric"] for r in recs} == {
        "readme_x_plus_3", "reduce_sum_min_vector", "dsl_map_blocks_1m"}


@pytest.mark.slow
def test_heavy_configs_smoke():
    r4 = baseline_configs.config4_resnet_inference(batch=2, image=64,
                                                   iters=1)
    assert r4["images_per_s"] > 0
    r5 = baseline_configs.config5_logreg_step(n=4096, d=8)
    assert r5["rows_per_s"] > 0


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CPU_ENV = dict(os.environ, JAX_PLATFORMS="cpu")


def test_daggregate_bench_light():
    # keeps the keyed-aggregation bench runnable (host + device key paths)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "daggregate_bench.py"),
         "20000", "500"],
        capture_output=True, text=True, timeout=300, env=_CPU_ENV)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    metrics = {r["metric"].split("x", 1)[1].split("_", 1)[1]
               for r in lines}
    assert metrics == {"host_keys", "host_keys_warm", "device_keys",
                       "device_keys_warm", "multikey_device"}, metrics


def test_tpu_pallas_smoke_fails_gracefully_off_chip():
    # chip-only kernel smoke: off-TPU it must exit 1 with a JSON reason,
    # not crash
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "tpu_pallas_smoke.py")],
        capture_output=True, text=True, timeout=240, env=_CPU_ENV)
    out = proc.stdout.strip().splitlines()
    assert out and json.loads(out[-1]).get("ok") is False
    assert proc.returncode == 1


def test_tpu_native_smoke_runs_on_cpu():
    # the native-core smoke runs off-chip too (cpu backend for both the
    # jax path and the C++ core), exiting 0 with parity
    from tensorframes_tpu import native_pjrt

    if not native_pjrt.available():
        pytest.skip("libtfrpjrt.so unavailable (no TF C++ libs)")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "tpu_native_smoke.py")],
        capture_output=True, text=True, timeout=500, env=_CPU_ENV)
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-1000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["ok"] is True and rec["native_platform"] == "cpu"
