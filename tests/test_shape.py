"""Shape layer unit tests (mirrors reference Shape.scala semantics)."""

import pytest

from tensorframes_tpu.shape import Shape, Unknown


def test_construct_and_repr():
    s = Shape(2, 3)
    assert s.dims == (2, 3)
    assert repr(s) == "[2,3]"
    assert repr(Shape(Unknown, 4)) == "[?,4]"
    assert Shape.empty.is_scalar
    assert repr(Shape.empty) == "[]"


def test_from_iterable_and_eq():
    assert Shape([2, 3]) == Shape(2, 3)
    assert Shape((2, 3)) == (2, 3)
    assert Shape(2, 3) != Shape(3, 2)


def test_negative_dims_normalize_to_unknown():
    assert Shape(-5, 3).dims == (Unknown, 3)


def test_prepend_tail_head_lead():
    cell = Shape(3)
    block = cell.prepend(Unknown)
    assert block == Shape(Unknown, 3)
    assert block.tail == cell
    assert block.head == Unknown
    assert block.with_lead(7) == Shape(7, 3)
    with pytest.raises(ValueError):
        Shape.empty.tail


def test_num_elements():
    assert Shape(2, 3).num_elements == 6
    assert Shape.empty.num_elements == 1
    assert Shape(Unknown, 3).num_elements is None


def test_more_precise_than():
    # concrete refines unknown
    assert Shape(5, 3).is_more_precise_than(Shape(Unknown, 3))
    assert Shape(5, 3).is_more_precise_than(Shape(5, 3))
    # unknown does not refine concrete
    assert not Shape(Unknown, 3).is_more_precise_than(Shape(5, 3))
    # rank mismatch
    assert not Shape(3).is_more_precise_than(Shape(3, 1))
    with pytest.raises(ValueError):
        Shape(Unknown).check_more_precise_than(Shape(4))


def test_merge():
    assert Shape(5, 3).merge(Shape(7, 3)) == Shape(Unknown, 3)
    assert Shape(5, 3).merge(Shape(5, 3)) == Shape(5, 3)
    assert Shape(5).merge(Shape(5, 1)) is None
    assert Shape(Unknown, 3).merge(Shape(2, 3)) == Shape(Unknown, 3)


def test_broadcast():
    assert Shape(5, 3).broadcast_with(Shape(3)) == Shape(5, 3)
    assert Shape(5, 1).broadcast_with(Shape(1, 3)) == Shape(5, 3)
    assert Shape.empty.broadcast_with(Shape(4)) == Shape(4)
    assert Shape(Unknown, 3).broadcast_with(Shape(3)) == Shape(Unknown, 3)
    # unknown against concrete stays unknown (the concrete side might be 1)
    assert Shape(Unknown).broadcast_with(Shape(7)) == Shape(Unknown)
    with pytest.raises(ValueError):
        Shape(2).broadcast_with(Shape(3))


def test_matches_concrete():
    assert Shape(Unknown, 3).matches_concrete((9, 3))
    assert not Shape(Unknown, 3).matches_concrete((9, 4))
    assert not Shape(Unknown, 3).matches_concrete((9,))
    assert Shape.empty.matches_concrete(())


def test_assert_concrete():
    assert Shape(2, 2).assert_concrete() == (2, 2)
    with pytest.raises(ValueError):
        Shape(Unknown).assert_concrete()
