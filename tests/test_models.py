"""Model zoo tests: logreg via the six-op API, ResNet-50 forward,
transformer LM (single-device and mesh-sharded train step)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.models import (LogisticRegression, ResNet50,
                                     TransformerConfig, TransformerLM)
from tensorframes_tpu.parallel.mesh import DeviceMesh, local_mesh
from jax.sharding import Mesh


def _logreg_frame(rng, n=200, d=4, parts=3):
    w_true = np.array([1.5, -2.0, 0.5, 3.0])
    x = rng.normal(size=(n, d))
    p = 1.0 / (1.0 + np.exp(-(x @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    return tft.frame({"features": x, "label": y}, num_partitions=parts)


class TestLogReg:
    def test_gradient_via_frame_matches_direct(self, rng):
        df = _logreg_frame(rng)
        model = LogisticRegression(4)
        params = {k: np.asarray(v) for k, v in model.init().items()}

        grad, loss = model.gradient_via_frame(params, df)

        merged = np.concatenate([b.dense("features") for b in df.blocks()])
        labels = np.concatenate([b.dense("label") for b in df.blocks()])
        direct = jax.grad(model.loss)(
            {"w": jnp.asarray(params["w"], jnp.float32),
             "b": jnp.asarray(params["b"], jnp.float32)},
            jnp.asarray(merged, jnp.float32),
            jnp.asarray(labels, jnp.float32))
        np.testing.assert_allclose(grad["w"], np.asarray(direct["w"]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(grad["b"], np.asarray(direct["b"]),
                                   rtol=1e-4, atol=1e-5)
        direct_loss = float(model.loss(
            {"w": jnp.asarray(params["w"], jnp.float32),
             "b": jnp.asarray(params["b"], jnp.float32)},
            jnp.asarray(merged, jnp.float32),
            jnp.asarray(labels, jnp.float32)))
        assert abs(loss - direct_loss) < 1e-4

    def test_fit_via_frame_learns(self, rng):
        df = _logreg_frame(rng, n=400)
        model = LogisticRegression(4)
        params, losses = model.fit_via_frame(df, steps=15, lr=1.0)
        assert losses[-1] < losses[0] * 0.7
        # learned weights correlate with the generating weights
        w = params["w"]
        assert w[3] > w[0] > 0 > w[1]

    def test_sharded_train_step(self, rng):
        mesh = local_mesh(8)
        model = LogisticRegression(4)
        step = model.make_sharded_train_step(mesh, lr=0.5)
        params = model.init()
        x = jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)
        w_true = jnp.array([1.5, -2.0, 0.5, 3.0])
        y = (jax.nn.sigmoid(x @ w_true) > 0.5).astype(jnp.float32)
        losses = []
        for _ in range(20):
            params, loss = step(params, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5


class TestResNet50:
    def test_forward_shape_and_determinism(self):
        model = ResNet50(num_classes=10)
        params = model.init()
        x = jnp.ones((2, 32, 32, 3), jnp.float32)
        logits = jax.jit(model.apply)(params, x)
        assert logits.shape == (2, 10)
        logits2 = jax.jit(model.apply)(params, jnp.ones((2, 32, 32, 3)))
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2))

    def test_stage_chain_equals_apply(self):
        # staged compilation (relay-survivable config 4): composing the
        # per-stage callables must be bit-identical to apply()
        model = ResNet50(num_classes=7)
        params = model.init()
        x = jnp.linspace(-1, 1, 2 * 32 * 32 * 3).reshape(
            (2, 32, 32, 3)).astype(jnp.float32)
        full = jax.jit(model.apply)(params, x)
        y = x
        for f in model.stage_fns():
            y = jax.jit(f)(params, y)
        np.testing.assert_allclose(np.asarray(y), np.asarray(full),
                                   rtol=1e-6, atol=1e-6)

    def test_infer_via_frame(self, rng):
        model = ResNet50(num_classes=5)
        params = model.init()
        imgs = rng.normal(size=(6, 32, 32, 3)).astype(np.float64)
        df = tft.frame({"image": imgs}, num_partitions=2)
        out = model.infer_via_frame(params, df, trim=True)
        rows = out.collect()
        assert len(rows) == 6
        assert np.asarray(rows[0]["logits"]).shape == (5,)
        # frame path agrees with direct application
        direct = np.asarray(model.apply(params,
                                        jnp.asarray(imgs, jnp.float32)))
        got = np.stack([np.asarray(r["logits"]) for r in rows])
        np.testing.assert_allclose(got, direct, rtol=2e-4, atol=2e-4)


CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64)


class TestTransformer:
    def test_forward_and_causality(self):
        model = TransformerLM(CFG)
        params = model.init()
        tok = jnp.zeros((1, 8), jnp.int32).at[0, 4].set(7)
        logits = model.apply(params, tok)
        assert logits.shape == (1, 8, 64)
        # causality: changing token at position 4 must not affect logits < 4
        tok2 = tok.at[0, 4].set(9)
        logits2 = model.apply(params, tok2)
        np.testing.assert_allclose(np.asarray(logits[0, :4]),
                                   np.asarray(logits2[0, :4]),
                                   rtol=1e-5, atol=1e-6)
        assert not np.allclose(np.asarray(logits[0, 4:]),
                               np.asarray(logits2[0, 4:]))

    def test_ring_attention_matches_local(self):
        model = TransformerLM(CFG)
        params = model.init()
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        local = model.apply(params, tok)

        devices = np.array(jax.devices()[:4]).reshape(1, 4)
        mesh = DeviceMesh(Mesh(devices, ("data", "seq")), data_axis="data")
        ringed = model.apply(params, tok, mesh=mesh, seq_axis="seq",
                             data_axis="data")
        np.testing.assert_allclose(np.asarray(local), np.asarray(ringed),
                                   rtol=2e-3, atol=2e-3)

    def test_ring_attention_composed_axes_matches_local(self):
        """dp+sp+tp composed: batch over data, seq ring, heads over model."""
        model = TransformerLM(CFG)
        params = model.init()
        tok = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 64)
        local = model.apply(params, tok)

        devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = DeviceMesh(Mesh(devices, ("data", "seq", "model")),
                          data_axis="data")
        ringed = model.apply(params, tok, mesh=mesh, seq_axis="seq",
                             data_axis="data", model_axis="model")
        np.testing.assert_allclose(np.asarray(local), np.asarray(ringed),
                                   rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("axes,shape,seq", [
        (("data",), (8,), None),                 # pure dp
        (("data", "model"), (2, 4), None),       # dp + tp
        (("data", "model", "seq"), (2, 2, 2), "seq"),  # dp + tp + sp
    ])
    def test_sharded_train_step(self, axes, shape, seq):
        devices = np.array(jax.devices()[:int(np.prod(shape))]
                           ).reshape(shape)
        mesh = DeviceMesh(Mesh(devices, axes), data_axis="data")
        model = TransformerLM(CFG)
        step, init_state = model.make_sharded_train_step(
            mesh, seq_axis=seq, learning_rate=1e-2)
        state = init_state(jax.random.PRNGKey(0))
        tok = jax.random.randint(jax.random.PRNGKey(2), (8, 8), 0, 64)
        tgt = jnp.roll(tok, -1, axis=1)
        losses = []
        for _ in range(5):
            state, loss = step(state, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # it learns (memorizes the batch)
        assert np.isfinite(losses).all()


class TestGenerate:
    """KV-cache autoregressive decoding."""

    def _model(self, vocab=32, layers=2):
        cfg = TransformerConfig(vocab_size=vocab, d_model=32, n_heads=4,
                                n_layers=layers, d_ff=64)
        m = TransformerLM(cfg)
        return m, m.init(jax.random.PRNGKey(1))

    def test_cached_forward_matches_apply(self):
        # teacher forcing through the cache (prefill + per-token decode)
        # must reproduce the plain causal forward exactly
        model, params = self._model()
        toks = jnp.asarray(
            np.random.default_rng(0).integers(0, 32, (2, 12)), jnp.int32)
        ref = model.apply(params, toks)                      # [2, 12, V]

        T = 12
        cache = model.init_cache(2, T)
        lg_pre, cache = model._forward_cached(params, cache, toks[:, :5],
                                              0, T)
        np.testing.assert_allclose(np.asarray(lg_pre),
                                   np.asarray(ref[:, :5]),
                                   rtol=2e-4, atol=2e-4)
        for pos in range(5, 12):
            lg, cache = model._forward_cached(
                params, cache, toks[:, pos:pos + 1], pos, T)
            np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                       np.asarray(ref[:, pos]),
                                       rtol=2e-4, atol=2e-4)

    def test_generate_shapes_and_determinism(self):
        model, params = self._model()
        prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        out = model.generate(params, prompt, max_new_tokens=5)
        assert out.shape == (2, 8)
        np.testing.assert_array_equal(np.asarray(out[:, :3]),
                                      np.asarray(prompt))
        again = model.generate(params, prompt, max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(again))

    def test_greedy_equals_stepwise_argmax(self):
        # greedy generate must match manually feeding argmax tokens back
        # through the full (uncached) forward — the cache cannot change
        # the distribution
        model, params = self._model()
        prompt = jnp.asarray([[7, 3, 11, 2]], jnp.int32)
        out = np.asarray(model.generate(params, prompt, max_new_tokens=4))
        toks = np.asarray(prompt)
        for _ in range(4):
            lg = model.apply(params, jnp.asarray(toks))
            nxt = np.argmax(np.asarray(lg[:, -1]), axis=-1)
            toks = np.concatenate([toks, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, toks)

    def test_sampling_needs_rng_and_runs(self):
        model, params = self._model()
        prompt = jnp.asarray([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="needs rng"):
            model.generate(params, prompt, 3, temperature=0.8)
        out = model.generate(params, prompt, 3, temperature=0.8,
                             rng=jax.random.PRNGKey(7))
        assert out.shape == (1, 5)
        assert (np.asarray(out) >= 0).all() and (np.asarray(out) < 32).all()

    def test_trained_model_continues_sequence(self):
        # train on +1/+2 modular sequences (the train_lm task), then ask
        # the model to continue a +1 prompt greedily
        from demos.train_lm import train

        mesh = local_mesh()
        vocab = 32
        cfg = TransformerConfig(vocab_size=vocab, d_model=64, n_heads=8,
                                n_layers=2, d_ff=128)
        model = TransformerLM(cfg)
        state, losses = train(mesh, n_steps=60, batch=16, seq_len=16,
                              vocab=vocab, config=cfg, learning_rate=3e-3)
        assert losses[-1] < 0.3, losses[-1]
        params = jax.device_put(state["params"])
        start = 5
        prompt = jnp.asarray(
            [[(start + i) % vocab for i in range(8)]], jnp.int32)
        out = np.asarray(model.generate(params, prompt, max_new_tokens=6))
        expect = [(start + i) % vocab for i in range(14)]
        assert out[0].tolist() == expect, (out[0].tolist(), expect)

    def test_generate_via_frame(self):
        model, params = self._model()
        prompts = np.asarray([[1, 2, 3, 4], [9, 8, 7, 6]], np.int64)
        df = tft.analyze(tft.frame({"prompt": prompts}))
        out = model.generate_via_frame(params, df, max_new_tokens=3)
        comp = out.blocks()[0].dense("completion")
        assert comp.shape == (2, 7)
        ref = np.asarray(model.generate(
            params, jnp.asarray(prompts, jnp.int32), 3))
        np.testing.assert_array_equal(np.asarray(comp), ref)

    def test_generate_via_frame_sampling_independent_blocks(self):
        # temperature>0 across partitions: different blocks must draw
        # different streams; identical frames must reproduce exactly
        model, params = self._model()
        prompts = np.asarray([[1, 2, 3, 4], [1, 2, 3, 4],
                              [1, 2, 3, 4], [1, 2, 3, 4]], np.int64)
        df = tft.analyze(tft.frame({"prompt": prompts}, num_partitions=2))
        key = jax.random.PRNGKey(3)
        out = model.generate_via_frame(params, df, max_new_tokens=6,
                                       temperature=1.5, rng=key)
        blocks = [b.dense("completion") for b in out.blocks()]
        assert len(blocks) == 2
        # same prompts, different block content is identical here — both
        # blocks hold the same rows, so streams coincide by the
        # deterministic-by-content contract...
        np.testing.assert_array_equal(blocks[0], blocks[1])
        # ...but a block with different content draws a different stream
        prompts2 = np.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], np.int64)
        df2 = tft.analyze(tft.frame({"prompt": prompts2},
                                    num_partitions=2))
        out2 = model.generate_via_frame(params, df2, max_new_tokens=6,
                                        temperature=1.5, rng=key)
        b2 = [b.dense("completion") for b in out2.blocks()]
        # the SAME prompt row [1,2,3,4] sits in both frames, but df2's
        # first block has different sibling rows than df's — the content
        # fold must give it a different sample stream (near-uniform model,
        # 6 tokens, vocab 32: collision odds ~1e-9). Deleting the fold_in
        # mix would make these byte-identical.
        assert not np.array_equal(blocks[0][0], b2[0][0]), (
            blocks[0][0], b2[0][0])
        # reproducibility: rerunning the same frame gives the same bytes
        again = model.generate_via_frame(params, df2, max_new_tokens=6,
                                         temperature=1.5, rng=key)
        a2 = [b.dense("completion") for b in again.blocks()]
        np.testing.assert_array_equal(b2[0], a2[0])
        np.testing.assert_array_equal(b2[1], a2[1])
