"""Adaptive-execution suite (tier-1; marker ``adaptive``;
``run-tests.sh --adaptive``).

The load-bearing contract: **every adaptive decision is bit-identical
to the static path**. Each equivalence case runs the same chain under
the default (``TFT_ADAPTIVE``/``TFT_RESULT_CACHE`` on — re-bucketed
block layouts, filter re-ordering, mid-plan re-plans, result-cache
hits) and under ``TFT_ADAPTIVE=0``/``TFT_RESULT_CACHE=0`` (the static
layout), and compares blocks value-for-value, dtype-for-dtype, block
boundaries included — across relational chains, streams, and source
shapes. On top of that:

- the block coalesce/split pass engages only after a measured forcing
  (feedback-gated), only on provably row-local chains, and restores
  the original block boundaries;
- conjunctive atom-proven filters re-order most-selective-first from
  observed selectivity; non-atom (cross-row) predicates never move;
- a result-cache hit re-forces with ZERO pipeline dispatches, is
  admitted two-touch, and invalidates on any source-version change
  (parquet append, ``uncache()``);
- preempt-aware serve admission parks a checkpointable whale instead
  of shedding the arrival (deadline assertions ride the ``timing``
  lane with ``timing_margin``).
"""

import os
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import io as tio
from tensorframes_tpu.plan import adaptive as _adaptive
from tensorframes_tpu.utils.tracing import counters

from conftest import timing_margin

pytestmark = pytest.mark.adaptive


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.delenv("TFT_ADAPTIVE", raising=False)
    monkeypatch.delenv("TFT_RESULT_CACHE", raising=False)
    monkeypatch.delenv("TFT_FUSE", raising=False)
    _adaptive.invalidate_results()
    yield
    _adaptive.invalidate_results()


def _snapshot(frame):
    out = []
    for b in frame.blocks():
        cols = {}
        for n, c in b.columns.items():
            cols[n] = list(c) if not isinstance(c, np.ndarray) else c
        out.append((b.num_rows, cols))
    return out


def _assert_identical(adaptive, static):
    assert len(adaptive) == len(static), "block count differs"
    for i, ((na, ca), (ns, cs)) in enumerate(zip(adaptive, static)):
        assert na == ns, f"block {i}: rows {na} != {ns}"
        assert set(ca) == set(cs), f"block {i}: columns differ"
        for n in cs:
            a, s = ca[n], cs[n]
            if isinstance(s, np.ndarray):
                assert isinstance(a, np.ndarray), (i, n)
                assert a.dtype == s.dtype, (i, n, a.dtype, s.dtype)
                assert a.shape == s.shape, (i, n, a.shape, s.shape)
                assert np.array_equal(a, s), (i, n)
            else:
                assert len(a) == len(s), (i, n)
                for x, y in zip(a, s):
                    assert np.array_equal(np.asarray(x), np.asarray(y))


def _static_snapshot(monkeypatch, build):
    monkeypatch.setenv("TFT_ADAPTIVE", "0")
    monkeypatch.setenv("TFT_RESULT_CACHE", "0")
    snap = _snapshot(build())
    monkeypatch.delenv("TFT_ADAPTIVE")
    monkeypatch.delenv("TFT_RESULT_CACHE")
    return snap


# ---------------------------------------------------------------------------
# leg 1: adaptive block sizing
# ---------------------------------------------------------------------------

class TestAdaptiveBlockSizing:
    def test_coalesce_engages_second_forcing_and_is_bit_identical(
            self, monkeypatch):
        # 48 dispatch-bound blocks; feedback gate: forcing 1 static,
        # forcing 2 re-bucketed; boundaries restored both times
        df = tft.frame({"x": np.arange(3000, dtype=np.float64)},
                       num_partitions=48)
        df.cache()
        f = lambda x: {"y": x * 2.0 + 1.0}        # noqa: E731
        p = lambda y: y > 500.0                   # noqa: E731

        def build():
            return df.map_rows(f).filter(p).select(["y"])

        before = counters.get("plan.adaptive_layouts")
        first = _snapshot(build())
        assert counters.get("plan.adaptive_layouts") == before, \
            "first forcing must run the static layout (no feedback yet)"
        second = _snapshot(build())
        assert counters.get("plan.adaptive_layouts") > before
        assert counters.get("plan.adaptive_coalesces") > 0
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(first, static)
        _assert_identical(second, static)

    def test_split_oversized_block_under_ledger(self, monkeypatch):
        from tensorframes_tpu import memory as _memory
        df = tft.frame({"x": np.arange(60_000, dtype=np.float64)},
                       num_partitions=2)
        df.cache()
        f = lambda x: {"y": x + 0.5}              # noqa: E731
        g = lambda y: {"z": y * 2.0}              # noqa: E731

        def build():
            return df.map_rows(f).map_rows(g).select(["z"])

        static = _static_snapshot(monkeypatch, build)
        # single-block frame: the canonical split case (one block far
        # over the ceiling) must also re-bucket
        one = tft.frame({"x": np.arange(60_000, dtype=np.float64)},
                        num_partitions=1)
        one.cache()

        def build_one():
            return one.map_rows(f).map_rows(g).select(["z"])

        static_one = _static_snapshot(monkeypatch, build_one)
        _memory.configure(limit_bytes=300_000)  # blocks ~480 KB each
        try:
            before = counters.get("plan.adaptive_splits")
            first = _snapshot(build())    # static (feedback gate)
            second = _snapshot(build())   # split layout
            assert counters.get("plan.adaptive_splits") > before
            before1 = counters.get("plan.adaptive_splits")
            first_one = _snapshot(build_one())
            second_one = _snapshot(build_one())
            assert counters.get("plan.adaptive_splits") > before1
        finally:
            _memory._reset()
        _assert_identical(first, static)
        _assert_identical(second, static)
        _assert_identical(first_one, static_one)
        _assert_identical(second_one, static_one)

    def test_empty_and_skewed_partitions_restore_boundaries(
            self, monkeypatch):
        # skewed layout with EMPTY partitions: 0-row originals must
        # come back as the verbatim empty-chain replay, in position
        blocks = ([np.arange(400.0)] + [np.empty(0)] * 3
                  + [np.arange(400.0, 405.0)] * 20)
        from tensorframes_tpu.frame import Block, TensorFrame
        schema = tft.frame({"x": blocks[0]}).schema
        bl = [Block({"x": a}, len(a)) for a in blocks]
        df = TensorFrame.from_blocks(bl, schema)
        df.cache()
        f = lambda x: {"y": x - 1.0}              # noqa: E731
        p = lambda y: y < 300.0                   # noqa: E731

        def build():
            return df.map_rows(f).filter(p)

        _snapshot(build())                        # feedback
        adaptive = _snapshot(build())
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(adaptive, static)
        assert len(adaptive) == len(bl)

    def test_cross_row_map_blocks_never_rebuckets(self, monkeypatch):
        # z = x - mean(x) is block-level state: coalescing would change
        # the mean, so the chain must stay on the static layout
        import jax.numpy as jnp
        df = tft.frame({"x": np.arange(900, dtype=np.float64)},
                       num_partitions=30)
        df.cache()
        f = lambda x: {"z": x - jnp.mean(x)}      # noqa: E731
        g = lambda z: {"w": z * 2.0}              # noqa: E731

        def build():
            return df.map_blocks(f).map_blocks(g).select(["w"])

        before = counters.get("plan.adaptive_layouts")
        first = _snapshot(build())
        second = _snapshot(build())
        assert counters.get("plan.adaptive_layouts") == before
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(first, static)
        _assert_identical(second, static)

    def test_adaptive_layout_over_join_leaf(self, monkeypatch):
        import jax.numpy as jnp
        from tensorframes_tpu import relational as rel
        left = tft.frame(
            {"k": np.arange(600, dtype=np.int64) % 50,
             "v": np.arange(600, dtype=np.float64)},
            num_partitions=24)
        right = tft.frame(
            {"k": np.arange(50, dtype=np.int64),
             "w": np.arange(50, dtype=np.float64) * 10.0})
        left.cache()
        right.cache()
        f = lambda v, w: {"s": v + jnp.asarray(w)}   # noqa: E731

        def build():
            out = rel.broadcast_join(left, right, on="k", how="left")
            return out.map_rows(f).select(["k", "s"])

        first = _snapshot(build())
        second = _snapshot(build())
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(first, static)
        _assert_identical(second, static)


# ---------------------------------------------------------------------------
# leg 2: re-planning from observed selectivity
# ---------------------------------------------------------------------------

class TestReplanning:
    def test_filter_reorder_is_bit_identical(self, monkeypatch):
        df = tft.frame({"z": np.arange(4000, dtype=np.float64)},
                       num_partitions=16)
        df.cache()
        p_all = lambda z: z >= 0.0                # noqa: E731
        p_few = lambda z: z < 15.0                # noqa: E731

        def build():
            return df.filter(p_all).filter(p_few)

        before = counters.get("plan.filter_reorders")
        first = _snapshot(build())      # records observed selectivity
        second = _snapshot(build())     # re-ordered plan
        assert counters.get("plan.filter_reorders") > before
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(first, static)
        _assert_identical(second, static)

    def test_cross_row_predicate_never_reorders(self, monkeypatch):
        # a predicate the atom extractor cannot prove row-local must
        # keep its position: reordering x > mean(x) would change it
        import jax.numpy as jnp
        df = tft.frame({"z": np.arange(1000, dtype=np.float64)},
                       num_partitions=4)
        df.cache()
        p_mean = lambda z: z > jnp.mean(z)        # noqa: E731
        p_few = lambda z: z < 900.0               # noqa: E731

        def build():
            return df.filter(p_mean).filter(p_few)

        before = counters.get("plan.filter_reorders")
        first = _snapshot(build())
        second = _snapshot(build())
        assert counters.get("plan.filter_reorders") == before
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(first, static)
        _assert_identical(second, static)

    def test_mid_plan_replan_on_shifted_distribution(self, monkeypatch):
        # q2 keeps everything on the warm-up data, then drops ~99% on
        # the real forcing: the probe block's observation deviates past
        # TFT_REPLAN_RATIO mid-run and the remaining stages re-plan
        monkeypatch.setenv("TFT_REPLAN_RATIO", "3")
        q1 = lambda v: v > -1.0                   # noqa: E731
        q2 = lambda v: v < 50.0                   # noqa: E731

        def chain(frame):
            return frame.filter(q1).filter(q2)

        warm = tft.frame({"v": np.arange(30, dtype=np.float64)},
                         num_partitions=30)
        warm.cache()
        _snapshot(chain(warm))          # priced ~keep-everything
        _snapshot(chain(warm))          # feedback for the shape

        big = tft.frame({"v": np.arange(6000, dtype=np.float64)},
                        num_partitions=30)
        big.cache()

        def build():
            return chain(big)

        before = counters.get("plan.replans")
        out = _snapshot(build())
        assert counters.get("plan.replans") > before, \
            "expected a mid-plan re-plan at the probe boundary"
        static = _static_snapshot(monkeypatch, build)
        _assert_identical(out, static)

    def test_join_cardinality_from_build_table_spans(self):
        from tensorframes_tpu import relational as rel
        # duplicate build keys: 4 rows per key — the sketch-based
        # estimate prices the expansion, not the old probe-row count
        left = tft.frame({"k": np.arange(100, dtype=np.int64) % 10,
                          "v": np.arange(100, dtype=np.float64)})
        right = tft.frame(
            {"k": np.repeat(np.arange(10, dtype=np.int64), 4),
             "w": np.arange(40, dtype=np.float64)})
        out = rel.broadcast_join(left, right, on="k", how="inner")
        est = out.estimated_rows()
        assert est is not None and 300 <= est <= 500  # true: 400
        # unique build keys stay exact (the PR 12 contract)
        right_u = tft.frame({"k": np.arange(10, dtype=np.int64),
                             "w": np.arange(10, dtype=np.float64)})
        out_u = rel.broadcast_join(left, right_u, on="k", how="left")
        assert out_u.estimated_rows() == 100

    def test_approx_key_distinct_probe(self):
        from tensorframes_tpu.relational.join import approx_key_distinct
        df = tft.frame({"k": (np.arange(5000) % 137).astype(np.int64),
                        "v": np.arange(5000, dtype=np.float64)},
                       num_partitions=4)
        assert approx_key_distinct(df, ["k"]) is None  # unforced
        df.cache()
        est = approx_key_distinct(df, ["k"])
        assert est is not None and abs(est - 137) / 137 < 0.15
        # cached per (keys, version)
        before = counters.get("relational.key_distinct_probes")
        approx_key_distinct(df, ["k"])
        assert counters.get("relational.key_distinct_probes") == before


# ---------------------------------------------------------------------------
# leg 3: the plan-fingerprint result cache
# ---------------------------------------------------------------------------

class TestResultCache:
    def test_two_touch_hit_with_zero_dispatches(self, monkeypatch):
        df = tft.frame({"x": np.arange(512, dtype=np.float64)},
                       num_partitions=8)
        df.cache()
        f = lambda x: {"y": x * 3.0}              # noqa: E731

        def build():
            return df.map_blocks(f).select(["y"])

        static = _static_snapshot(monkeypatch, build)
        hits0 = counters.get("plan.result_cache_hits")
        _snapshot(build())        # 1st: seen
        _snapshot(build())        # 2nd: interned
        assert counters.get("plan.result_cache_hits") == hits0
        before = (counters.get("pipeline.submitted"),
                  counters.get("pipeline.drained"))
        frame = build()
        out = _snapshot(frame)    # 3rd: HIT
        after = (counters.get("pipeline.submitted"),
                 counters.get("pipeline.drained"))
        assert counters.get("plan.result_cache_hits") == hits0 + 1
        assert after == before, "a cache hit must dispatch nothing"
        assert frame._plan_info and "result cache" in frame._plan_info[0]
        _assert_identical(out, static)

    def test_off_switch(self, monkeypatch):
        monkeypatch.setenv("TFT_RESULT_CACHE", "0")
        df = tft.frame({"x": np.arange(64, dtype=np.float64)})
        df.cache()
        f = lambda x: {"y": x + 1.0}              # noqa: E731

        def build():
            return df.map_blocks(f)

        hits0 = counters.get("plan.result_cache_hits")
        for _ in range(4):
            _snapshot(build())
        assert counters.get("plan.result_cache_hits") == hits0

    def test_uncache_reversions_and_misses(self):
        df = tft.frame({"x": np.arange(64, dtype=np.float64)})
        df.cache()
        f = lambda x: {"y": x + 1.0}              # noqa: E731

        def build():
            return df.map_blocks(f)

        _snapshot(build())
        _snapshot(build())        # interned
        hits0 = counters.get("plan.result_cache_hits")
        df.uncache()              # source re-versioned
        df.cache()
        _snapshot(build())
        assert counters.get("plan.result_cache_hits") == hits0

    def test_stale_invalidation_after_parquet_append(
            self, monkeypatch, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = str(tmp_path / "t.parquet")
        tio.write_parquet(
            tft.frame({"x": np.arange(32, dtype=np.float64)},
                      num_partitions=2), path)
        f = lambda x: {"y": x * 2.0}              # noqa: E731

        def build():
            return tio.read_parquet(path).map_blocks(f).select(["y"])

        static = _static_snapshot(monkeypatch, build)
        _snapshot(build())
        second = _snapshot(build())               # interned
        _assert_identical(second, static)
        hits0 = counters.get("plan.result_cache_hits")
        out3 = _snapshot(build())                 # hit
        assert counters.get("plan.result_cache_hits") == hits0 + 1
        _assert_identical(out3, static)
        # append a row group: footer identity changes -> the old entry
        # can never hit; the re-read sees the same pinned range
        time.sleep(0.01)
        with pq.ParquetWriter(
                path, pa.table(
                    {"x": np.arange(40, dtype=np.float64)}).schema) \
                as w:
            w.write_table(
                pa.table({"x": np.arange(40, dtype=np.float64)}))
        hits1 = counters.get("plan.result_cache_hits")
        fresh = _snapshot(build())
        assert counters.get("plan.result_cache_hits") == hits1
        assert sum(n for n, _ in fresh) == 40

    def test_streaming_batches_never_pollute_the_cache(self):
        from tensorframes_tpu import stream
        stats0 = _adaptive.result_cache_stats()["entries"]

        def batches():
            for i in range(6):
                yield {"x": np.arange(8, dtype=np.float64) + i}

        f = lambda x: {"y": x + 1.0}              # noqa: E731
        h = stream.from_source(stream.GeneratorSource(batches())) \
            .map_blocks(f).start(name="rc-pollute")
        h.run()
        assert _adaptive.result_cache_stats()["entries"] == stats0

    def test_lru_eviction_under_entry_budget(self, monkeypatch):
        monkeypatch.setenv("TFT_RESULT_CACHE_ENTRIES", "2")
        df = tft.frame({"x": np.arange(32, dtype=np.float64)})
        df.cache()
        fns = [(lambda k: (lambda x: {"y": x + float(k)}))(k)
               for k in range(4)]

        def build(k):
            return df.map_blocks(fns[k])

        for k in range(4):
            _snapshot(build(k))
            _snapshot(build(k))   # intern each
        assert _adaptive.result_cache_stats()["entries"] <= 2
        assert counters.get("plan.result_cache_evictions") >= 2


# ---------------------------------------------------------------------------
# streams: adaptive batch sizing
# ---------------------------------------------------------------------------

class TestStreamBatchSizing:
    def _rows(self, frames):
        out = []
        for fr in frames:
            for b in fr.blocks():
                out.extend(np.asarray(b.columns["y"]).tolist())
        return out

    def test_adaptive_batches_same_rows(self, monkeypatch):
        from tensorframes_tpu import stream
        f = lambda x: {"y": x * 2.0}              # noqa: E731

        def batches():
            for i in range(24):
                yield {"x": np.arange(4, dtype=np.float64) + 4 * i}

        h1 = stream.from_source(stream.GeneratorSource(batches())) \
            .map_blocks(f).start(name="ab-static")
        h1.run()
        want = self._rows(h1.collect_updates())

        h2 = stream.from_source(stream.GeneratorSource(batches())) \
            .map_blocks(f).start(name="ab-adaptive",
                                 batch_rows="adaptive")
        h2.run()
        got = self._rows(h2.collect_updates())
        assert got == want
        m = h2.metrics()
        assert m["rows"] == 24 * 4
        assert m["batches"] <= 24  # coalescing can only merge

    def test_fixed_batch_rows_coalesce_and_kill_switch(
            self, monkeypatch):
        from tensorframes_tpu import stream
        f = lambda x: {"y": x + 1.0}              # noqa: E731

        def batches():
            for i in range(12):
                yield {"x": np.arange(2, dtype=np.float64) + 2 * i}

        h = stream.from_source(stream.GeneratorSource(batches())) \
            .map_blocks(f).start(name="ab-fixed", batch_rows=8)
        h.run()
        assert h.metrics()["rows"] == 24
        assert h.metrics()["batches"] < 12

        monkeypatch.setenv("TFT_ADAPTIVE", "0")
        h0 = stream.from_source(stream.GeneratorSource(batches())) \
            .map_blocks(f).start(name="ab-fixed-off", batch_rows=8)
        h0.run()
        assert h0.metrics()["batches"] == 12  # pass-through under =0

    def test_windowed_aggregation_bit_identical_with_batching(
            self, monkeypatch):
        from tensorframes_tpu import stream

        def batches():
            for i in range(16):
                yield {"k": (np.arange(4) % 2).astype(np.int64),
                       "v": np.arange(4, dtype=np.float64) + i,
                       "ts": np.full(4, float(i))}

        def run(**kw):
            h = stream.from_source(
                stream.GeneratorSource(batches())) \
                .group_by("k") \
                .aggregate({"v": "sum"}, window=stream.tumbling(4.0),
                           time_col="ts") \
                .start(name=f"ab-win-{len(kw)}", **kw)
            h.run()
            rows = []
            for fr in h.collect_updates():
                for r in fr.collect():
                    rows.append((float(r["window_start"]),
                                 int(r["k"]), float(r["v"])))
            return sorted(rows)

        want = run()
        got = run(batch_rows="adaptive")
        assert got == want


# ---------------------------------------------------------------------------
# serve: preempt-aware admission
# ---------------------------------------------------------------------------

@pytest.mark.timing
class TestPreemptAwareAdmission:
    @pytest.fixture(autouse=True)
    def _pin_memory(self):
        # the fake watermark below must never be latched into the
        # process memory manager's derived budget: pin an explicitly
        # unlimited manager for the duration, then drop the singleton
        # so later tests re-resolve against the real environment
        from tensorframes_tpu import memory as _memory
        _memory.configure(limit_bytes=0)
        yield
        _memory._reset()

    def test_whale_parks_instead_of_shedding(self, monkeypatch):
        import threading

        from tensorframes_tpu import serve
        from tensorframes_tpu.engine import preempt as _preempt
        from tensorframes_tpu.observability import device as _obs_device
        release = threading.Event()
        parked = threading.Event()
        whale_running = threading.Event()

        # a synthetic watermark so admission is enforceable on CPU:
        # roomy until the whale starts, full while it runs, roomy
        # again once it parked (its buffers moved off-device)
        def fake_watermark():
            live = 900 if (whale_running.is_set()
                           and not parked.is_set()) else 100
            return {"live_bytes": live, "limit_bytes": 1000}

        monkeypatch.setattr(_obs_device, "watermark", fake_watermark)
        monkeypatch.setenv("TFT_SERVE_ADMISSION_WAIT_S",
                           str(timing_margin(5.0)))

        class Whale:
            def blocks(self):
                # a fake long-running forcing that honors preemption at
                # its "block boundary"
                whale_running.set()
                sc = _preempt.current_scope()
                for i in range(4000):
                    if sc is not None and sc.preempt_requested \
                            and _preempt.boundary(sc, i > 0):
                        parked.set()
                        _preempt.park(sc, [], 4000, None)  # raises
                    if release.wait(0.002):
                        break
                return []

        with serve.QueryScheduler(workers=2, name="adm-preempt") as s:
            # the whale's footprint (800 B) plausibly covers any later
            # arrival's shortfall — the plausibility guard lets it park
            q_whale = s.submit(Whale(), tenant="big",
                               est_rows=10.0, est_bytes=800)
            t0 = time.monotonic()
            while q_whale.state != "running" \
                    and time.monotonic() - t0 < timing_margin(5.0):
                time.sleep(0.005)
            assert q_whale.state == "running"
            before = counters.get("serve.admission_preempts")
            small = tft.frame({"x": np.arange(8, dtype=np.float64)})
            q2 = s.submit(small, tenant="small",
                          est_rows=8.0, est_bytes=500)
            assert parked.wait(timing_margin(5.0)), \
                "the whale was never asked to park"
            assert counters.get("serve.admission_preempts") > before
            # the arrival admits into the cleared headroom and finishes
            q2.result(timeout=timing_margin(10.0))
            assert q2.state == "done"
            release.set()

    def test_no_victim_still_sheds(self, monkeypatch):
        from tensorframes_tpu import serve
        from tensorframes_tpu.resilience import AdmissionDeadline
        from tensorframes_tpu.observability import device as _obs_device
        monkeypatch.setattr(
            _obs_device, "watermark",
            lambda: {"live_bytes": 990, "limit_bytes": 1000})
        monkeypatch.setenv("TFT_SERVE_ADMISSION_WAIT_S", "0.1")
        with serve.QueryScheduler(workers=0, name="adm-shed") as s:
            df = tft.frame({"x": np.arange(8, dtype=np.float64)})
            q = s.submit(df, tenant="t", est_rows=8.0, est_bytes=10_000)
            assert s.step()
            with pytest.raises(AdmissionDeadline):
                q.result(timeout=timing_margin(2.0))
