"""TensorFrame + marshalling tests."""

import numpy as np
import pytest

from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.frame import Block, Row, TensorFrame, frame
from tensorframes_tpu.marshal import (
    columns_to_rows, infer_physical_shape, rows_to_columns)
from tensorframes_tpu.schema import Field, Schema
from tensorframes_tpu.shape import Shape, Unknown


def test_infer_physical_shape():
    assert infer_physical_shape(12, Shape(Unknown, 3)) == (4, 3)
    assert infer_physical_shape(6, Shape(2, 3)) == (2, 3)
    assert infer_physical_shape(0, Shape(Unknown, 3)) == (0, 3)
    with pytest.raises(ValueError, match="cannot fill"):
        infer_physical_shape(7, Shape(Unknown, 3))
    with pytest.raises(ValueError, match="does not match"):
        infer_physical_shape(5, Shape(2, 3))
    with pytest.raises(ValueError, match="multiple unknown"):
        infer_physical_shape(6, Shape(Unknown, Unknown))


def test_rows_to_columns_fast_and_back():
    s = Schema.of(x="double", n="int")
    rows = [(1.0, 1), (2.0, 2), (3.0, 3)]
    cols = rows_to_columns(rows, s)
    assert cols["x"].dtype == np.float64
    assert cols["n"].dtype == np.int32
    back = columns_to_rows(cols, s)
    assert back == rows


def test_columns_to_rows_fast_matches_slow():
    # the fast path (column-at-a-time tolist/zip) and the slow per-cell
    # reference loop must agree on every column kind: numeric scalars,
    # tensor cells, object (string) columns, and ragged list columns
    s = Schema([Field("x", dt.double), Field("n", dt.int32),
                Field("m", dt.double, sql_rank=1)])
    cols = {"x": np.array([1.5, 2.5, 3.5]),
            "n": np.array([1, 2, 3], np.int32),
            "m": np.arange(6.0).reshape(3, 2)}
    fastr = columns_to_rows(cols, s, fast=True)
    slowr = columns_to_rows(cols, s, fast=False)
    assert len(fastr) == len(slowr) == 3
    for fr, sr in zip(fastr, slowr):
        assert fr[0] == sr[0] and isinstance(fr[0], float)
        assert fr[1] == sr[1] and isinstance(fr[1], int)
        np.testing.assert_array_equal(fr[2], sr[2])

    so = Schema([Field("k", dt.string), Field("v", dt.double, sql_rank=1)])
    cols2 = {"k": np.array(["a", "b"], object),
             "v": [np.array([1.0, 2.0]), np.array([3.0])]}  # ragged
    fast2 = columns_to_rows(cols2, so, fast=True)
    slow2 = columns_to_rows(cols2, so, fast=False)
    for fr, sr in zip(fast2, slow2):
        assert fr[0] == sr[0] and isinstance(fr[0], str)
        np.testing.assert_array_equal(fr[1], sr[1])


def test_rows_to_columns_ragged():
    s = Schema([Field("v", dt.double, sql_rank=1)])
    rows = [([1.0, 2.0],), ([3.0],)]
    cols = rows_to_columns(rows, s)
    assert isinstance(cols["v"], list)
    assert [len(a) for a in cols["v"]] == [2, 1]


def test_null_cell_rejected():
    s = Schema.of(x="double")
    with pytest.raises(ValueError, match="[Nn]ull"):
        rows_to_columns([(1.0,), (None,)], s, fast=False)


def test_frame_from_rows_schema_inference():
    df = frame([(1.0, 1), (2.0, 2)], columns=["x", "n"])
    assert df.schema["x"].dtype is dt.double
    assert df.schema["n"].dtype is dt.int64  # python int -> long, Spark-style
    assert df.count() == 2
    r = df.first()
    assert r["x"] == 1.0 and r[1] == 1


def test_frame_from_columns_and_partitions():
    df = frame({"x": np.arange(10.0)}, num_partitions=3)
    assert df.num_partitions == 3
    sizes = [b.num_rows for b in df.blocks()]
    assert sum(sizes) == 10 and max(sizes) - min(sizes) <= 1
    assert [r["x"] for r in df.collect()] == list(np.arange(10.0))


def test_vector_column_block_shape():
    df = frame({"v": np.ones((6, 3))}, num_partitions=2)
    assert df.schema["v"].block_shape == Shape(Unknown, 3)
    assert df.blocks()[0].dense("v").shape == (3, 3)


def test_ragged_dense_raises():
    s = Schema([Field("v", dt.double, sql_rank=1)])
    df = TensorFrame.from_rows([([1.0, 2.0],), ([3.0],)], schema=s)
    b = df.blocks()[0]
    assert b.is_ragged("v")
    with pytest.raises(ValueError, match="map_rows"):
        b.dense("v")


def test_select_and_row_access():
    df = frame([(1.0, 10), (2.0, 20)], columns=["x", "n"])
    sel = df.select(["n"])
    assert sel.columns == ["n"]
    assert [r["n"] for r in sel.collect()] == [10, 20]
    with pytest.raises(KeyError):
        df.collect()[0]["zz"]


def test_repartition_roundtrip():
    df = frame({"x": np.arange(7.0)}, num_partitions=2).repartition(3)
    assert sorted(r["x"] for r in df.collect()) == list(np.arange(7.0))
    assert len(df.blocks()) == 3


def test_group_by_validates():
    df = frame({"x": np.arange(4.0)})
    with pytest.raises(KeyError):
        df.group_by("nope")
    g = df.group_by("x")
    assert g.keys == ["x"]


def test_empty_partition_representable():
    df = frame({"x": np.arange(2.0)}, num_partitions=1)
    blocks = df.blocks() + [Block({"x": np.empty((0,))}, 0)]
    df2 = TensorFrame.from_blocks(blocks, df.schema)
    assert df2.count() == 2


def test_block_concat_mixed():
    s = Schema.of(x="double")
    b1 = Block({"x": np.array([1.0, 2.0])})
    b2 = Block({"x": np.array([3.0])})
    c = Block.concat([b1, b2], s)
    assert c.num_rows == 3
    np.testing.assert_array_equal(c.dense("x"), [1.0, 2.0, 3.0])


def test_columns_to_rows_length_mismatch_raises():
    s = Schema.of(x="double", n="int")
    cols = {"x": np.array([1.0, 2.0, 3.0]), "n": np.array([1, 2], np.int32)}
    with pytest.raises(ValueError, match="disagree on row count"):
        columns_to_rows(cols, s, fast=True)
    with pytest.raises(ValueError, match="disagree on row count"):
        columns_to_rows(cols, s, fast=False)


class TestOrderBy:
    def test_single_key(self):
        import tensorframes_tpu as tft

        df = tft.frame({"x": np.array([3.0, 1.0, 2.0]),
                        "y": np.array([30.0, 10.0, 20.0])},
                       num_partitions=2)
        rows = df.order_by("x").collect()
        assert [(r["x"], r["y"]) for r in rows] == [
            (1.0, 10.0), (2.0, 20.0), (3.0, 30.0)]

    def test_multi_key_and_stability(self):
        import tensorframes_tpu as tft

        k1 = np.array([1, 0, 1, 0, 1], np.int64)
        x = np.array([5.0, 4.0, 3.0, 2.0, 1.0])
        df = tft.frame({"k": k1, "x": x})
        rows = df.order_by("k", "x").collect()
        assert [(r["k"], r["x"]) for r in rows] == [
            (0, 2.0), (0, 4.0), (1, 1.0), (1, 3.0), (1, 5.0)]

    def test_descending_stable_with_strings(self):
        import tensorframes_tpu as tft

        k = np.array(["b", "a", "b", "a"], object)
        tag = np.array([0.0, 1.0, 2.0, 3.0])
        df = tft.frame({"k": k, "tag": tag})
        rows = df.order_by("k", descending=True).collect()
        # primary: b before a; ties keep original order (stable)
        assert [(r["k"], r["tag"]) for r in rows] == [
            ("b", 0.0), ("b", 2.0), ("a", 1.0), ("a", 3.0)]

    def test_vector_columns_follow(self):
        import tensorframes_tpu as tft

        df = tft.analyze(tft.frame({"x": np.array([2.0, 1.0]),
                                    "v": np.array([[2., 2.], [1., 1.]])}))
        rows = df.order_by("x").collect()
        np.testing.assert_array_equal(rows[0]["v"], [1.0, 1.0])

    def test_descending_float_nan_stays_last(self):
        # NaN placement must agree with the mesh dsort: descending on a
        # float key sinks NaN rows to the END (value negation), not the
        # front (which rank-negation via np.unique would produce)
        import tensorframes_tpu as tft

        x = np.array([3.0, np.nan, 1.0, 2.0])
        df = tft.frame({"x": x})
        got = [r["x"] for r in df.order_by("x", descending=True).collect()]
        assert got[:3] == [3.0, 2.0, 1.0]
        assert np.isnan(got[3])

    def test_validation(self):
        import tensorframes_tpu as tft

        df = tft.analyze(tft.frame({"v": np.ones((3, 2))}))
        with pytest.raises(ValueError, match="scalar column"):
            df.order_by("v")
        with pytest.raises(KeyError, match="No column"):
            df.order_by("nope")
        with pytest.raises(ValueError, match="at least one"):
            df.order_by()


class TestLimitSampleShow:
    def _df(self, n=10, parts=3):
        import tensorframes_tpu as tft

        return tft.frame({"x": np.arange(float(n))}, num_partitions=parts)

    def test_limit(self):
        df = self._df()
        assert [r["x"] for r in df.limit(4).collect()] == [0.0, 1.0, 2.0,
                                                           3.0]
        assert df.limit(0).collect() == []
        assert df.limit(100).count() == 10
        with pytest.raises(ValueError, match=">= 0"):
            df.limit(-1)

    def test_limit_preserves_string_columns(self):
        import tensorframes_tpu as tft

        df = tft.frame({"k": np.array(["a", "b", "c"], object),
                        "x": np.arange(3.0)})
        rows = df.limit(2).collect()
        assert [(r["k"], r["x"]) for r in rows] == [("a", 0.0), ("b", 1.0)]

    def test_sample_deterministic_and_bounds(self):
        df = self._df(1000, parts=4)
        s1 = df.sample(0.3, seed=7).collect()
        s2 = df.sample(0.3, seed=7).collect()
        assert [r["x"] for r in s1] == [r["x"] for r in s2]
        assert 200 < len(s1) < 400          # ~300, loose bounds
        assert df.sample(0.0).count() == 0
        assert df.sample(1.0).count() == 1000
        with pytest.raises(ValueError, match="not in"):
            df.sample(1.5)

    def test_show_prints_table(self, capsys):
        import tensorframes_tpu as tft

        df = tft.analyze(tft.frame({"x": np.arange(3.0),
                                    "v": np.ones((3, 6))}))
        df.show(2)
        out = capsys.readouterr().out
        assert "| x" in out and "| v" in out
        assert "..." in out          # long vector cells elide
        assert out.count("\n") >= 6  # frame lines + 2 rows
