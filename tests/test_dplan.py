"""Distributed logical plan (``tensorframes_tpu/plan/dist.py``):
lazy d-op chains fused into ONE GSPMD program per mesh stage.

The acceptance spine: every chain shape recorded on a lazy frame
(``frame.lazy()``) collects BIT-IDENTICAL to the same chain run through
the eager per-op d-ops (which is also exactly what ``TFT_FUSE=0``
executes), with one mesh dispatch instead of one per op and zero
inter-op host transfers; terminal monoid ``dreduce_blocks`` /
``daggregate`` fold into the same program; an injected ``device:1``
loss mid-fused-stage shrinks/reshards/re-runs correctly; ledger
pressure spills resident shard edges that fault back bit-identically.
Deadline assertions belong in the ``timing`` lane — this suite has
none by design.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import parallel as par
from tensorframes_tpu import memory
from tensorframes_tpu.observability import events as obs_events
from tensorframes_tpu.parallel import elastic
from tensorframes_tpu.plan import dist as dplan
from tensorframes_tpu.plan.nodes import observed_selectivity
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils import tracing
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.dplan


@pytest.fixture(scope="module")
def mesh8():
    import jax
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return par.local_mesh(8)


@pytest.fixture(autouse=True)
def _clean():
    counters.reset()
    faults.reset()
    elastic._tracker.clear()
    yield
    faults.reset()
    elastic._tracker.clear()
    tracing.disable()
    memory._reset()


def _frame(n=40, keys=5, strings=False):
    cols = {"k": (np.arange(n) % keys).astype(np.int64),
            "x": np.arange(n).astype(np.int64),
            "f": np.arange(n, dtype=np.float64) * 0.5}
    if strings:
        cols["s"] = np.array([f"n{i}" for i in range(n)], object)
    return tft.frame(cols)


def _cols(frame):
    """Collected columns of a (distributed) frame as exact numpy."""
    tf = frame.collect_frame()
    blocks = tf.blocks()
    out = {}
    for f in tf.schema:
        parts = [np.asarray(b.columns[f.name], object)
                 if not f.dtype.tensor else np.asarray(b.dense(f.name))
                 for b in blocks]
        out[f.name] = np.concatenate(parts) if parts else np.empty(0)
    return out


def _assert_identical(got, ref):
    assert set(got) == set(ref)
    for n in ref:
        g, r = got[n], ref[n]
        assert g.dtype == r.dtype, (n, g.dtype, r.dtype)
        assert g.shape == r.shape, (n, g.shape, r.shape)
        if g.dtype == object:
            assert list(g) == list(r), n
        else:
            # bit-identical, not approximately equal
            assert np.array_equal(g, r), n


def _run_chain(chain, dist, lazy: bool):
    return chain(dist.lazy() if lazy else dist)


CHAINS = {
    "maps": lambda d: par.dmap_blocks(
        lambda z: {"w": z + 1}, par.dmap_blocks(
            lambda x: {"z": x * 2}, d)),
    "map_filter_map": lambda d: par.dmap_blocks(
        lambda z: {"w": z + 1}, par.dfilter(
            lambda z: z % 3 == 0, par.dmap_blocks(
                lambda x: {"z": x * 2}, d))),
    "filter_first": lambda d: par.dmap_blocks(
        lambda x: {"z": x + 10}, par.dfilter(lambda x: x % 2 == 0, d)),
    "multi_filter": lambda d: par.dfilter(
        lambda x: x < 30, par.dfilter(lambda x: x % 2 == 0, d)),
    "select_prunes": lambda d: par.dmap_blocks(
        lambda x: {"z": x * 3}, d).select(["z"]),
    "float_row_local": lambda d: par.dmap_blocks(
        lambda f: {"g": f * 1.5 + 0.25}, d),
    "filter_to_zero": lambda d: par.dmap_blocks(
        lambda x: {"z": x + 1}, par.dfilter(lambda x: x < 0, d)),
}


# ---------------------------------------------------------------------------
# fused vs per-op bit-identity
# ---------------------------------------------------------------------------

class TestEquivalence:
    @pytest.mark.parametrize("shape", sorted(CHAINS))
    def test_chain_bit_identical(self, mesh8, shape):
        dist = par.distribute(_frame(), mesh8)
        chain = CHAINS[shape]
        ref = _cols(_run_chain(chain, dist, lazy=False))
        got = _cols(_run_chain(chain, dist, lazy=True))
        _assert_identical(got, ref)

    def test_fuse_off_is_the_per_op_path(self, mesh8, monkeypatch):
        dist = par.distribute(_frame(), mesh8)
        monkeypatch.setenv("TFT_FUSE", "0")
        assert dist.lazy() is dist  # the kill switch: no recording at all
        ref = _cols(CHAINS["map_filter_map"](dist.lazy()))
        monkeypatch.delenv("TFT_FUSE")
        got = _cols(CHAINS["map_filter_map"](dist.lazy()))
        _assert_identical(got, ref)

    def test_string_ride_along_through_fused_filter(self, mesh8):
        dist = par.distribute(_frame(strings=True), mesh8)
        chain = CHAINS["map_filter_map"]
        ref = _cols(chain(dist))
        got = _cols(chain(dist.lazy()))
        _assert_identical(got, ref)
        assert got["s"].dtype == object

    def test_shard_valid_input_frame(self, mesh8):
        # the chain's SOURCE already carries per-shard validity (a
        # prior eager dfilter): the fused program masks per shard
        dist = par.dfilter(lambda x: x % 3 != 1,
                           par.distribute(_frame(), mesh8))
        assert dist.shard_valid is not None
        chain = CHAINS["map_filter_map"]
        _assert_identical(_cols(chain(dist.lazy())), _cols(chain(dist)))

    def test_empty_shards(self, mesh8):
        # 3 rows on 8 shards: most shards hold only pad rows
        dist = par.distribute(_frame(n=3), mesh8)
        chain = CHAINS["map_filter_map"]
        _assert_identical(_cols(chain(dist.lazy())), _cols(chain(dist)))

    def test_vector_cells(self, mesh8):
        df = tft.frame({"x": np.arange(16).astype(np.int64),
                        "v": np.arange(48, dtype=np.float64)
                        .reshape(16, 3)})
        dist = par.distribute(df, mesh8)

        def chain(d):
            return par.dmap_blocks(
                lambda m: {"s": m * 2.0},
                par.dmap_blocks(lambda x, v: {"m": x[:, None] * v}, d))

        _assert_identical(_cols(chain(dist.lazy())), _cols(chain(dist)))

    def test_trim_map_materializes_chain(self, mesh8):
        # a trim (global) map is not recordable: the pending chain
        # forces fused, the trim runs eagerly on the resident result
        dist = par.distribute(_frame(), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x * 2}, dist.lazy())
        out = par.dmap_blocks(lambda z: {"t": z.sum()[None]}, lz,
                              trim=True)
        ref = par.dmap_blocks(
            lambda z: {"t": z.sum()[None]},
            par.dmap_blocks(lambda x: {"z": x * 2}, dist), trim=True)
        assert int(out.columns["t"][0]) == int(ref.columns["t"][0])

    def test_record_time_validation_parity(self, mesh8):
        from tensorframes_tpu.engine import ops as eops
        dist = par.distribute(_frame(), mesh8)
        with pytest.raises(ValueError, match="collides"):
            par.dmap_blocks(lambda x: {"x": x}, dist.lazy())
        with pytest.raises(KeyError):
            dist.lazy().select(["nope"])
        # same error text as the eager op for a predicate naming a
        # string column (raised at RECORD time, not at force)
        with pytest.raises(eops.InvalidTypeError, match="non-tensor"):
            par.dfilter(lambda s: s,
                        par.distribute(_frame(strings=True),
                                       mesh8).lazy())


# ---------------------------------------------------------------------------
# folded terminal reductions
# ---------------------------------------------------------------------------

class TestFoldedReductions:
    def test_reduce_int_bit_identical(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        fetches = {"x": "sum", "z": "max", "k": "min"}
        ref = par.dreduce_blocks(
            fetches, par.dmap_blocks(lambda x: {"z": x * 2}, dist))
        d0 = counters.get("mesh.dispatches")
        got = par.dreduce_blocks(
            fetches, par.dmap_blocks(lambda x: {"z": x * 2},
                                     dist.lazy()))
        assert counters.get("mesh.dispatches") - d0 == 1
        for n in fetches:
            assert got[n].dtype == ref[n].dtype
            assert np.array_equal(got[n], ref[n]), n

    def test_reduce_after_filter(self, mesh8):
        dist = par.distribute(_frame(), mesh8)

        def chain(d):
            return par.dfilter(lambda x: x % 2 == 0, d)

        ref = par.dreduce_blocks({"x": "sum"}, chain(dist))
        got = par.dreduce_blocks({"x": "sum"}, chain(dist.lazy()))
        assert np.array_equal(got["x"], ref["x"])

    def test_reduce_empty_after_filter_raises(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        lz = par.dfilter(lambda x: x < 0, dist.lazy())
        with pytest.raises(ValueError, match="empty"):
            par.dreduce_blocks({"x": "sum"}, lz)

    def test_reduce_unknown_column_and_combiner(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x + 1}, dist.lazy())
        with pytest.raises(KeyError, match="No column"):
            par.dreduce_blocks({"nope": "sum"}, lz)
        with pytest.raises(KeyError, match="Unknown combiner"):
            par.dreduce_blocks({"x": "median"}, lz)

    def test_generic_reduce_materializes(self, mesh8):
        dist = par.distribute(_frame(), mesh8)

        def combine(x_input):
            return {"x": x_input.sum(axis=0)}

        ref = par.dreduce_blocks(
            combine, par.dmap_blocks(lambda x: {"z": x * 2}, dist)
            .select(["x"]))
        got = par.dreduce_blocks(
            combine, par.dmap_blocks(lambda x: {"z": x * 2},
                                     dist.lazy()).select(["x"]))
        assert np.array_equal(got["x"], ref["x"])

    def test_aggregate_folded_matches_eager(self, mesh8):
        dist = par.distribute(_frame(), mesh8)

        def chain(d):
            return par.dmap_blocks(lambda x: {"v": x * 3},
                                   d).select(["k", "v"])

        ref = par.daggregate({"v": "sum"}, chain(dist), "k")
        d0 = counters.get("mesh.dispatches")
        got = par.daggregate({"v": "sum"}, chain(dist.lazy()), "k")
        assert counters.get("mesh.dispatches") - d0 == 1
        assert got.collect() == ref.collect()

    def test_aggregate_with_filter_falls_back_correctly(self, mesh8):
        # a filter invalidates the source key->id layout: the chain
        # forces fused, the aggregation runs eagerly on the result
        dist = par.distribute(_frame(), mesh8)

        def chain(d):
            return par.dfilter(lambda x: x % 2 == 0, d)

        ref = par.daggregate({"x": "sum"}, chain(dist), "k")
        got = par.daggregate({"x": "sum"}, chain(dist.lazy()), "k")
        assert got.collect() == ref.collect()

    def test_aggregate_hot_key_salted_exact(self, mesh8):
        n = 64
        k = np.zeros(n, np.int64)
        k[: n // 4] = np.arange(n // 4) % 3 + 1  # one dominant key 0
        df = tft.frame({"k": k, "x": np.arange(n).astype(np.int64)})
        dist = par.distribute(df, mesh8)
        lz = par.dmap_blocks(lambda x: {"v": x + 1}, dist.lazy()) \
            .select(["k", "v"])
        got = par.daggregate({"v": "sum"}, lz, "k")
        ref = par.daggregate(
            {"v": "sum"},
            par.dmap_blocks(lambda x: {"v": x + 1}, dist)
            .select(["k", "v"]), "k")
        assert got.collect() == ref.collect()
        assert counters.get("mesh.salted_keys") >= 1


# ---------------------------------------------------------------------------
# laziness, dispatch counts, host transfers
# ---------------------------------------------------------------------------

class TestLaziness:
    def test_recording_does_not_dispatch(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        d0 = counters.get("mesh.dispatches")
        lz = CHAINS["map_filter_map"](dist.lazy())
        assert counters.get("mesh.dispatches") == d0
        lz.collect_frame()
        assert counters.get("mesh.dispatches") == d0 + 1

    def test_at_least_4x_fewer_dispatches(self, mesh8):
        dist = par.distribute(_frame(), mesh8)

        def four_op(d):
            d = par.dmap_blocks(lambda x: {"z": x * 2}, d)
            d = par.dfilter(lambda z: z % 3 == 0, d)
            d = par.dmap_blocks(lambda z: {"w": z + 1}, d)
            return par.dreduce_blocks({"w": "sum"}, d)

        d0 = counters.get("mesh.dispatches")
        ref = four_op(dist)
        eager_n = counters.get("mesh.dispatches") - d0
        d1 = counters.get("mesh.dispatches")
        got = four_op(dist.lazy())
        fused_n = counters.get("mesh.dispatches") - d1
        assert np.array_equal(got["w"], ref["w"])
        assert eager_n == 4
        assert fused_n == 1  # >= 4x fewer (the acceptance bar is 2x)

    def test_zero_interstage_host_bytes_when_fused(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        chain = CHAINS["map_filter_map"]
        h0 = counters.get("mesh.interstage_host_bytes")
        chain(dist).collect_frame()
        eager_bytes = counters.get("mesh.interstage_host_bytes") - h0
        h1 = counters.get("mesh.interstage_host_bytes")
        chain(dist.lazy()).collect_frame()
        fused_bytes = counters.get("mesh.interstage_host_bytes") - h1
        assert eager_bytes > 0      # dfilter's counts readback
        assert fused_bytes == 0     # counts stay traced in-program

    # stable fetch objects: computations (and therefore fused
    # programs) cache per fetches object, like every per-op path —
    # a chain rebuilt from the same callables re-dispatches one
    # compiled program
    _mk = staticmethod(lambda x: {"z": x * 2})
    _fl = staticmethod(lambda z: z % 3 == 0)
    _mk2 = staticmethod(lambda z: {"w": z + 1})

    def test_program_cache_hit_on_reforcing(self, mesh8):
        dist = par.distribute(_frame(), mesh8)

        def chain(d):
            return par.dmap_blocks(
                TestLaziness._mk2, par.dfilter(
                    TestLaziness._fl, par.dmap_blocks(
                        TestLaziness._mk, d)))

        chain(dist.lazy()).collect_frame()
        built = counters.get("dplan.fused_programs")
        chain(dist.lazy()).collect_frame()  # same comps, same shapes
        assert counters.get("dplan.fused_programs") == built

    def test_resident_passthrough_skips_program_io(self, mesh8):
        # a map-only chain's untouched column chains buffer-to-buffer:
        # the SAME device array object, no copy, no repartition
        dist = par.distribute(_frame(), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x * 2}, dist.lazy())
        assert lz.columns["k"] is dist.columns["k"]


# ---------------------------------------------------------------------------
# elastic recovery through fused programs
# ---------------------------------------------------------------------------

class TestElastic:
    def test_device_loss_mid_fused_stage(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        chain = CHAINS["map_filter_map"]
        ref = _cols(chain(dist))
        lz = chain(dist.lazy())
        tracing.enable()
        try:
            with faults.inject("device", 1):
                lz.count()  # forces mid-inject: the loss hits the
                #             fused dispatch boundary
            t = obs_events.last_query()
        finally:
            tracing.disable()
        _assert_identical(_cols(lz), ref)
        assert lz.mesh.num_devices == 7
        assert counters.get("mesh.devices_lost") == 1
        assert counters.get("mesh.reshard_rows") > 0
        assert t is not None and t.op == "dfused"
        shr = [e for e in t.events if e.etype == "mesh_shrink"]
        assert len(shr) == 1 and shr[0].args["devices_after"] == 7

    def test_device_loss_on_folded_reduce(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x * 2}, dist.lazy())
        ref = par.dreduce_blocks(
            {"z": "sum"}, par.dmap_blocks(lambda x: {"z": x * 2}, dist))
        with faults.inject("device", 1):
            got = par.dreduce_blocks({"z": "sum"}, lz)
        assert np.array_equal(got["z"], ref["z"])
        assert counters.get("mesh.devices_lost") == 1

    def test_elastic_disabled_raises(self, mesh8, monkeypatch):
        monkeypatch.setenv("TFT_ELASTIC", "0")
        dist = par.distribute(_frame(), mesh8)
        lz = CHAINS["maps"](dist.lazy())
        with faults.inject("device", 1):
            with pytest.raises(faults.InjectedFault):
                lz.collect_frame()


# ---------------------------------------------------------------------------
# fallbacks
# ---------------------------------------------------------------------------

class TestFallback:
    def test_permanent_fault_replays_per_op(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        chain = CHAINS["map_filter_map"]
        ref = _cols(chain(dist))
        lz = chain(dist.lazy())
        f0 = counters.get("dplan.fallbacks")
        with faults.inject("dmap", 1, transient=False):
            got = _cols(lz)
        _assert_identical(got, ref)
        assert counters.get("dplan.fallbacks") == f0 + 1

    def test_transient_fault_retries_through_fused(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        chain = CHAINS["maps"]
        ref = _cols(chain(dist))
        lz = chain(dist.lazy())
        f0 = counters.get("dplan.fallbacks")
        with faults.inject("dmap", 1):  # transient: the policy retries
            got = _cols(lz)
        _assert_identical(got, ref)
        assert counters.get("dplan.fallbacks") == f0  # no fallback

    def test_fuse_disabled_after_recording_replays(self, mesh8,
                                                   monkeypatch):
        dist = par.distribute(_frame(), mesh8)
        lz = CHAINS["map_filter_map"](dist.lazy())
        monkeypatch.setenv("TFT_FUSE", "0")  # flipped between record
        got = _cols(lz)                      # and force
        monkeypatch.delenv("TFT_FUSE")
        _assert_identical(got, _cols(CHAINS["map_filter_map"](dist)))


# ---------------------------------------------------------------------------
# memory ledger: resident shard edges
# ---------------------------------------------------------------------------

class TestLedger:
    def test_resident_edges_spill_and_fault_back(self, mesh8):
        memory.configure(limit_bytes=10 ** 9)
        dist = par.distribute(
            tft.frame({"x": np.arange(256, dtype=np.float64)}), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x + 1.0},
                             dist.lazy()).select(["z"])
        ref = _cols(lz)
        cols = lz.columns
        assert type(cols).__name__ == "SpillableColumns"
        freed = cols.mem_spill()   # ledger-driven spill of the edge
        assert freed > 0
        _assert_identical(_cols(lz), ref)  # fault-back bit-identical

    def test_passthrough_result_not_double_registered(self, mesh8):
        # a map-only chain's untouched column IS the source's device
        # buffer: wrapping the result in a second spillable would
        # double-count those bytes in the ledger, so the result stays
        # a plain dict (the source's own registration covers it)
        memory.configure(limit_bytes=10 ** 9)
        dist = par.distribute(
            tft.frame({"x": np.arange(64, dtype=np.float64)}), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x + 1.0}, dist.lazy())
        assert type(lz.columns).__name__ != "SpillableColumns"
        assert lz.columns["x"] is dist.columns["x"]

    def test_ledger_pressure_spills_fused_result(self, mesh8):
        # process-wide admission pressure pushes the forced fused
        # result (a registered resident) out through the ledger LRU;
        # the next collect faults it back bit-identically
        mgr = memory.configure(limit_bytes=100_000)
        dist = par.distribute(
            tft.frame({"x": np.arange(512, dtype=np.float64)}), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x + 1.0},
                             dist.lazy()).select(["z"])
        ref = _cols(lz)
        s0 = counters.get("memory.spills")
        mgr.make_room(10 ** 9)  # an admission squeeze spills residents
        assert counters.get("memory.spills") > s0
        assert lz.columns.mem_is_spilled()
        _assert_identical(_cols(lz), ref)

    def test_lazy_estimate_without_forcing(self, mesh8):
        from tensorframes_tpu.memory.estimate import dist_frame_estimate
        dist = par.distribute(_frame(), mesh8)
        lz = par.dmap_blocks(lambda f: {"g": f * 2.0}, dist.lazy())
        rows, nbytes = dist_frame_estimate(lz)
        assert lz._forced is None  # estimating must not force
        assert rows == 40
        assert nbytes is not None and nbytes > 0


# ---------------------------------------------------------------------------
# feedback selectivity (ROADMAP 2a, first slice)
# ---------------------------------------------------------------------------

class TestFeedbackSelectivity:
    def test_dfilter_records_observed_selectivity(self, mesh8):
        dist = par.distribute(_frame(n=60, keys=6), mesh8)
        pred = lambda x: x % 3 == 0  # noqa: E731 - the shared predicate
        from tensorframes_tpu.engine.ops import _filter_computation
        comp = _filter_computation(pred, dist.schema)
        assert observed_selectivity(comp) is None
        par.dfilter(pred, dist)
        sel = observed_selectivity(comp)
        assert sel is not None and abs(sel - 1 / 3) < 0.05

    def test_fused_filter_records_and_estimates_sharpen(self, mesh8):
        dist = par.distribute(_frame(n=64, keys=4), mesh8)
        pred = lambda x: x % 4 == 0  # noqa: E731
        lz = par.dfilter(pred, dist.lazy())
        up_rows, _ = lz._dplan_node.estimate()
        assert up_rows == 64  # upper bound before any observation
        lz.collect_frame()    # the forcing observes rows-in/rows-out
        lz2 = par.dfilter(pred, dist.lazy())
        rows, _ = lz2._dplan_node.estimate()
        assert rows is not None and rows == pytest.approx(16, rel=0.05)

    def test_single_device_filter_node_sharpens(self):
        df = tft.frame({"x": np.arange(100, dtype=np.float64)})
        pred = lambda x: x < 25.0  # noqa: E731
        f1 = df.filter(pred)
        r_up, _ = f1._plan_node.estimate()
        assert r_up == 100
        f1.blocks()  # force: observes selectivity 0.25
        f2 = df.filter(pred)
        r_obs, _ = f2._plan_node.estimate()
        assert r_obs == pytest.approx(25, rel=0.05)

    def test_downstream_cached_estimate_sharpens_too(self):
        # the epoch-keyed estimate cache: a node DOWNSTREAM of the
        # filter, whose estimate was cached before the observation,
        # re-prices after it (admission must not keep the upper bound
        # forever)
        df = tft.frame({"x": np.arange(100, dtype=np.float64)})
        pred = lambda x: x < 10.0  # noqa: E731
        chain = df.filter(pred).map_blocks(lambda x: {"z": x * 2.0})
        r_before, _ = chain._plan_node.estimate()  # caches upper bound
        assert r_before == 100
        df.filter(pred).blocks()  # observe selectivity 0.1 elsewhere
        r_after, _ = chain._plan_node.estimate()
        assert r_after == pytest.approx(10, rel=0.05)

    def test_record_time_row_aligned_error(self, mesh8):
        # the bad-argument error fires at RECORD time without
        # executing the pending chain first
        dist = par.distribute(_frame(), mesh8)
        lz = par.dmap_blocks(lambda x: {"z": x + 1}, dist.lazy())
        with pytest.raises(ValueError, match="row_aligned=False"):
            par.dmap_blocks(lambda z: {"w": z}, lz, row_aligned=False)
        assert lz._forced is None  # nothing ran


# ---------------------------------------------------------------------------
# explain / observability
# ---------------------------------------------------------------------------

class TestExplain:
    def test_lazy_explain_renders_plan_section(self, mesh8):
        dist = par.distribute(_frame(), mesh8)
        lz = CHAINS["map_filter_map"](dist.lazy())
        text = lz.explain()
        assert "dplan" in text
        assert "1 fused GSPMD program" in text
        assert "compacted in-program" in text

    def test_fuse_off_explain_names_the_reason(self, mesh8,
                                               monkeypatch):
        dist = par.distribute(_frame(), mesh8)
        lz = CHAINS["maps"](dist.lazy())
        monkeypatch.setenv("TFT_FUSE", "0")
        text = lz.explain()
        assert "TFT_FUSE=0" in text

    def test_trace_report_shows_fused_stage(self, mesh8):
        from tensorframes_tpu.observability.report import render
        dist = par.distribute(_frame(), mesh8)
        lz = CHAINS["map_filter_map"](dist.lazy())
        tracing.enable()
        try:
            lz.collect_frame()
        finally:
            tracing.disable()
        t = obs_events.last_query()
        assert t is not None and t.op == "dfused"
        text = render(t)
        assert "ONE GSPMD program" in text


# ---------------------------------------------------------------------------
# distributed streams on the mesh
# ---------------------------------------------------------------------------

class TestStreamMesh:
    def _run(self, mesh):
        from tensorframes_tpu import stream

        def gen():
            for i in range(8):
                yield {"k": (np.arange(8) % 2).astype(np.int64),
                       "v": (np.arange(8) + i).astype(np.int64),
                       "ts": np.full(8, float(i))}

        agg = (stream.from_source(stream.GeneratorSource(gen()))
               .group_by("k")
               .aggregate({"v": "sum"}, window=stream.tumbling(4.0),
                          time_col="ts", mesh=mesh))
        h = agg.start()
        rows = []
        while not h.done():
            h.step()
            for f in h.collect_updates():
                rows.extend(f.collect())
        return rows

    def test_windowed_stream_on_mesh_matches_single_device(self, mesh8):
        ref = self._run(None)
        m0 = counters.get("stream.mesh_folds")
        got = self._run(mesh8)
        assert counters.get("stream.mesh_folds") > m0
        assert got == ref  # integer sums: exact across shard counts

    def test_one_fused_dispatch_per_batch_fold(self, mesh8):
        d0 = counters.get("mesh.dispatches")
        m0 = counters.get("stream.mesh_folds")
        self._run(mesh8)
        folds = counters.get("stream.mesh_folds") - m0
        assert counters.get("mesh.dispatches") - d0 == folds
