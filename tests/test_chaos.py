"""Chaos-schedule / invariant-auditor / quarantine suite (tier-1;
markers ``chaos`` + ``invariants``; ``run-tests.sh --chaos`` runs both
lanes standalone).

Proves the composed-robustness contract:

- seeded chaos schedules (``resilience/chaos.py``): the decision for a
  site's n-th consult is a pure hash of ``(seed, site, n)`` — same
  seed, same firings, exactly; spec parsing rejects typos instead of
  arming vacuous drills; firings arm the SAME one-shot budgets as
  scripted faults (site-correct classifier shaping included) and are
  flight-recorded for replay;
- the fault-site table (``faults.sites()``): every armed-able site is
  driven here, arming an unknown site warns loudly, and the
  conformance meta-tests keep the docs + test-coverage in sync with
  the table;
- cross-cutting invariant auditors (``resilience/invariants.py``):
  always-on counts + flight-records, strict raises a classified
  ``InvariantViolation``; per-query row-conservation ledger,
  checkpoint cursor checks, exchange conservation (raises in EVERY
  mode), auditor crashes are violations too;
- poison-query quarantine (``serve/quarantine.py``): a streak of
  permanent failures fast-rejects the fingerprint with a classified
  ``QueryQuarantined``; TTL expiry admits ONE probe; success resets;
  ``tft.unquarantine()`` lifts; surfaced in health()/doctor()/
  serve_report();
- persist artifact checksums (``memory/persist.py``): bit-rot that
  still unpickles goes COLD (``memory.persist_corrupt``), never wrong;
  both shapes of the ``disk`` fault site;
- the bounded acceptance drill (``tools/chaos_soak.py``): a mixed
  workload under a seeded multi-site schedule is bit-identical to the
  fault-free run, leaks nothing, classifies every surfaced failure,
  and replays on its seed.
"""

import os
import pickle
import sys
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import observability as obs
from tensorframes_tpu import serve
from tensorframes_tpu.engine import pipeline as engine_pipeline
from tensorframes_tpu.memory import persist as _persist
from tensorframes_tpu.memory.checkpoint import QueryCheckpoint
from tensorframes_tpu.observability import flight as obs_flight
from tensorframes_tpu.resilience import chaos, error_kind, faults, invariants
from tensorframes_tpu.resilience.classify import (InvariantViolation,
                                                  QueryQuarantined,
                                                  is_transient)
from tensorframes_tpu.resilience.faults import InjectedFault
from tensorframes_tpu.serve import QueryScheduler, TenantQuota
from tensorframes_tpu.serve import quarantine
from tensorframes_tpu.utils import tracing

pytestmark = pytest.mark.chaos

# tools/ is not a package; the soak driver is imported by path so the
# tier-1 drill and the standalone soak run the exact same code
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import chaos_soak  # noqa: E402

# the literal twin of faults.sites().keys() — kept literal ON PURPOSE:
# the conformance meta-test greps test sources for quoted site names,
# so every site must appear as a string in at least one test file, and
# test_site_table_matches_literals pins this list to the real table
ALL_SITES = ("batch", "cluster_init", "compile", "device", "disk",
             "dispatch", "dmap", "drain", "oom", "pad_compile",
             "perf", "pjrt_execute", "preempt", "worker")


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.stop()
    faults.reset()
    quarantine.reset()
    tracing.counters.reset()
    obs.clear_ring()
    yield
    serve.shutdown_default_scheduler()
    chaos.stop()
    faults.reset()
    quarantine.reset()
    tracing.counters.reset()
    obs.clear_ring()
    assert engine_pipeline.current_slot_pool() is None


# -- chaos schedules -------------------------------------------------------

class TestChaosSchedule:
    def test_same_seed_fires_identically(self):
        a = chaos.ChaosSchedule(5, 0.2, ["compile"])
        b = chaos.ChaosSchedule(5, 0.2, ["compile"])
        c = chaos.ChaosSchedule(6, 0.2, ["compile"])
        for _ in range(300):
            a.consult("compile")
            b.consult("compile")
            c.consult("compile")
        faults.reset()  # consult() arms one-shot budgets as it fires
        assert a.firings() == b.firings()
        assert a.firings(), "rate 0.2 over 300 consults never fired"
        assert a.firings() != c.firings()
        assert a.fingerprint() == b.fingerprint()

    def test_check_integration_replays(self):
        spec = "seed:5,rate:0.2,sites:compile"
        raised = []
        for _ in range(2):
            hits = []
            with chaos.inject(spec) as sched:
                for i in range(200):
                    try:
                        faults.check("compile")
                    except InjectedFault:
                        hits.append(i)
                assert len(sched.firings()) == len(hits)
            raised.append(hits)
        assert raised[0], "chaos schedule never fired through check()"
        assert raised[0] == raised[1]

    def test_parse_spec(self):
        s = chaos.parse("seed:42,rate:0.5,sites:device|worker|disk")
        assert (s.seed, s.rate) == (42, 0.5)
        assert s.sites == ("device", "worker", "disk")
        # defaults: seed 0, rate 0.05
        d = chaos.parse("sites:compile")
        assert (d.seed, d.rate) == (0, 0.05)
        with pytest.raises(ValueError, match="malformed"):
            chaos.parse("seed=42,sites:compile")
        with pytest.raises(ValueError, match="unknown TFT_CHAOS key"):
            chaos.parse("sede:42,sites:compile")
        with pytest.raises(ValueError, match="unknown fault site"):
            chaos.parse("sites:compile|tyop")
        with pytest.raises(ValueError, match="at least one site"):
            chaos.parse("seed:1,rate:0.5")
        with pytest.raises(ValueError, match="rate"):
            chaos.ChaosSchedule(1, 1.5, ["compile"])
        with pytest.raises(ValueError, match="rate"):
            chaos.ChaosSchedule(1, 0.0, ["compile"])

    def test_firings_shaped_for_classifiers(self):
        # a chaos fault must be indistinguishable from a scripted one:
        # the firing arms the site's shaped message, so the downstream
        # classifier sees the kind the site's recovery path keys on
        for site, kind in (("oom", "oom"), ("device", "device_lost"),
                           ("worker", "worker_lost")):
            with chaos.inject(chaos.ChaosSchedule(1, 1.0, [site])):
                with pytest.raises(InjectedFault) as ei:
                    faults.check(site)
            assert error_kind(ei.value) == kind, site

    def test_stop_disarms_pending_firings(self):
        sched = chaos.start(chaos.ChaosSchedule(1, 1.0, ["dispatch"]))
        assert sched.consult("dispatch")  # fires: arms a one-shot budget
        assert faults.active("dispatch") == 1
        chaos.stop()
        assert faults.active("dispatch") == 0, (
            "stop() must disarm fired-but-unconsumed budgets")
        assert chaos.active() is None

    def test_env_knob(self, monkeypatch):
        monkeypatch.setattr(chaos, "_env_armed", False)
        monkeypatch.setenv("TFT_CHAOS", "seed:9,rate:0.5,sites:compile")
        chaos.maybe_start_from_env()
        try:
            sched = chaos.active()
            assert sched is not None
            assert (sched.seed, sched.rate) == (9, 0.5)
            assert sched.sites == ("compile",)
        finally:
            chaos.stop()
        # memoized: a second call with the schedule stopped stays off
        chaos.maybe_start_from_env()
        assert chaos.active() is None

    def test_firings_flight_recorded(self):
        with chaos.inject(chaos.ChaosSchedule(1, 1.0, ["compile"])):
            with pytest.raises(InjectedFault):
                faults.check("compile")
        recs = obs_flight.recent(kind="chaos.fire")
        assert recs, "chaos firing was not flight-recorded"
        assert recs[-1]["site"] == "compile"
        assert recs[-1]["seed"] == 1
        assert recs[-1]["step"] >= 1
        assert tracing.counters.get("chaos.fired") >= 1
        assert tracing.counters.get("chaos.compile.fired") >= 1

    def test_may_fire(self):
        assert not faults.may_fire("compile")
        faults.arm("compile", 1)
        assert faults.may_fire("compile")
        faults.reset("compile")
        assert not faults.may_fire("compile")
        with chaos.inject(chaos.ChaosSchedule(1, 0.01, ["compile"])):
            # named by the schedule: COULD fire, even at a tiny rate
            assert faults.may_fire("compile")
            assert not faults.may_fire("dispatch")
        assert not faults.may_fire("compile")


# -- the fault-site table --------------------------------------------------

class TestFaultSites:
    def test_site_table_matches_literals(self):
        assert tuple(sorted(faults.sites())) == ALL_SITES

    def test_sites_returns_copy(self):
        got = faults.sites()
        got["bogus"] = "nope"
        assert "bogus" not in faults.sites()

    def test_unknown_site_warns_loudly(self):
        before = tracing.counters.get("faults.unknown_sites")
        faults.arm("tyop", 1)
        try:
            assert tracing.counters.get("faults.unknown_sites") == before + 1
        finally:
            faults.reset("tyop")

    @pytest.mark.parametrize("site", [s for s in ALL_SITES if s != "perf"])
    def test_every_site_arms_and_raises(self, site):
        with faults.inject(site):
            with pytest.raises(InjectedFault) as ei:
                faults.check(site)
        kind = error_kind(ei.value)
        expect = {"oom": "oom", "device": "device_lost",
                  "worker": "worker_lost", "disk": "permanent"}
        assert kind == expect.get(site, "transient"), site
        # non-transient sites must never reach the retry loop
        if site in ("oom", "device", "worker", "disk"):
            assert not is_transient(ei.value)
        assert faults.active(site) == 0

    def test_perf_site_sleeps_never_raises(self, monkeypatch):
        monkeypatch.setenv("TFT_FAULT_PERF_S", "0.001")
        with faults.inject("perf"):
            assert faults.slowdown("perf") >= 0.001
            assert faults.slowdown("perf") == 0.0  # budget spent


# -- invariant auditors ----------------------------------------------------

class TestInvariants:
    pytestmark = pytest.mark.invariants

    def test_custom_auditor_always_on_counts(self):
        invariants.register("testaud", lambda point: ["book unbalanced"])
        try:
            found = invariants.audit("test.point")
        finally:
            invariants.unregister("testaud")
        assert found == ["[testaud] book unbalanced"]
        assert tracing.counters.get("invariants.violations") == 1
        assert tracing.counters.get("invariants.testaud.violations") == 1
        recs = obs_flight.recent(kind="invariant.violation")
        assert recs and recs[-1]["auditor"] == "testaud"
        assert recs[-1]["point"] == "test.point"

    def test_strict_mode_raises_classified(self):
        invariants.register("testaud", lambda point: ["book unbalanced"])
        try:
            with invariants.strict():
                assert invariants.strict_mode()
                with pytest.raises(InvariantViolation) as ei:
                    invariants.audit("test.point")
        finally:
            invariants.unregister("testaud")
        assert error_kind(ei.value) == "invariant"
        assert "testaud" in str(ei.value)
        assert not invariants.strict_mode()

    def test_chaos_schedule_implies_strict(self):
        assert not invariants.strict_mode()
        with chaos.inject(chaos.ChaosSchedule(1, 0.01, ["compile"])):
            assert invariants.strict_mode()
        assert not invariants.strict_mode()

    def test_auditor_crash_is_a_violation(self):
        def broken(point):
            raise RuntimeError("auditor bug")
        invariants.register("broken", broken)
        try:
            found = invariants.audit("test.point")
        finally:
            invariants.unregister("broken")
        assert len(found) == 1 and "auditor crashed" in found[0]
        assert tracing.counters.get("invariants.broken.violations") == 1

    def test_disabled_bypass(self, monkeypatch):
        monkeypatch.setenv("TFT_INVARIANTS", "0")
        assert not invariants.enabled()
        invariants.register("testaud", lambda point: ["unbalanced"])
        try:
            assert invariants.audit("test.point") == []
        finally:
            invariants.unregister("testaud")
        assert tracing.counters.get("invariants.violations") == 0
        # check() cold-paths without counting when disabled
        assert invariants.check(False, "testaud", "nope") is False
        assert tracing.counters.get("invariants.violations") == 0

    def test_env_strict_knob(self, monkeypatch):
        monkeypatch.setenv("TFT_INVARIANTS_STRICT", "1")
        assert invariants.strict_mode()
        with pytest.raises(InvariantViolation):
            invariants.violate("testaud", "unbalanced")

    def test_conserve_raises_in_every_mode(self):
        assert not invariants.strict_mode()  # even always-on raises
        with pytest.raises(InvariantViolation) as ei:
            invariants.conserve(10, 8, "test.exchange")
        assert error_kind(ei.value) == "invariant"
        assert tracing.counters.get("invariants.rows.violations") == 1
        invariants.conserve(10, 10, "test.exchange")  # balanced: quiet

    def test_row_ledger_balanced(self):
        with invariants.row_ledger(10, "test.query"):
            invariants.note_filtered(4)
            invariants.note_emitted(6)
        assert tracing.counters.get("invariants.violations") == 0

    def test_row_ledger_unbalanced_counts(self):
        with invariants.row_ledger(10, "test.query"):
            invariants.note_filtered(4)
            invariants.note_emitted(5)  # 10 != 5 + 4
        assert tracing.counters.get("invariants.rows.violations") == 1

    def test_row_ledger_unbalanced_strict_raises(self):
        with pytest.raises(InvariantViolation):
            with invariants.strict():
                with invariants.row_ledger(10, "test.query"):
                    invariants.note_emitted(5)
                    invariants.note_filtered(4)

    def test_row_ledger_taint_skips_check(self):
        with invariants.strict():
            with invariants.row_ledger(10, "test.query"):
                invariants.note_emitted(5)
                invariants.taint_rows("resume restored a prior prefix")
        assert tracing.counters.get("invariants.rows.tainted") == 1
        assert tracing.counters.get("invariants.violations") == 0

    def test_real_filter_query_balances(self):
        # the production row ledger: plan/execute opens it around a
        # row-local fused chain (atom-proven filter + map_rows), filter
        # stages note their masked-out rows, the close balances
        df = tft.frame({"x": np.arange(30.0)}, num_partitions=3)
        with invariants.strict():
            out = df.map_rows(lambda x: {"z": x * 2.0}).filter(
                lambda z: z > 10.0)
            blocks = out.blocks()  # forces the fused chain
        vals = np.concatenate(
            [np.asarray(b.columns["z"]) for b in blocks])
        np.testing.assert_allclose(np.sort(vals),
                                   np.arange(12.0, 60.0, 2.0))
        assert tracing.counters.get("invariants.rows.violations") == 0
        assert tracing.counters.get("invariants.audits") >= 1

    def test_checkpoint_park_cursor_check(self):
        cp = QueryCheckpoint("q-cursor")
        cp.park_stream([np.arange(3.0), np.arange(3.0)], total=1,
                       tag="stream-a")
        assert tracing.counters.get(
            "invariants.checkpoint.violations") == 1

    def test_checkpoint_resume_cursor_cold_paths(self):
        cp = QueryCheckpoint("q-cursor2")
        # an inconsistent cursor (more parked blocks than the stream
        # has) must discard to a cold re-run, never resume dup rows
        cp._parked = ([("junk",), ("junk",)], 1, "stream-a")
        before = tracing.counters.get("serve.checkpoint_discards")
        assert cp.resume_stream(total=1, tag="stream-a") is None
        assert tracing.counters.get(
            "serve.checkpoint_discards") == before + 1
        assert tracing.counters.get(
            "invariants.checkpoint.violations") == 1

    def test_scheduler_quiesce_audit_clean(self):
        with QueryScheduler(workers=1, name="inv-clean") as sched:
            df = tft.frame({"x": np.arange(16.0)}, num_partitions=2)
            fut = sched.submit(df, lambda x: {"z": x * 2.0}, tenant="t")
            fut.result(timeout=60)
            with invariants.strict():
                assert invariants.audit("test.quiesce") == []
        with invariants.strict():
            assert invariants.audit("test.close") == []
        assert tracing.counters.get("invariants.violations") == 0


# -- poison-query quarantine -----------------------------------------------

class TestQuarantine:
    def test_streak_quarantines_and_classifies(self):
        fp = "fp-poison-1"
        boom = ValueError("deterministic plan bug")
        quarantine.note_failure(fp, boom)
        quarantine.note_failure(fp, boom)
        quarantine.check(fp)  # below threshold: admitted
        quarantine.note_failure(fp, boom)  # 3rd: quarantined
        assert tracing.counters.get("serve.quarantines") == 1
        with pytest.raises(QueryQuarantined) as ei:
            quarantine.check(fp)
        assert error_kind(ei.value) == "quarantined"
        assert not is_transient(ei.value)
        assert "unquarantine" in str(ei.value)
        assert tracing.counters.get("serve.quarantined") == 1
        st = quarantine.status()
        assert fp in st["active"]
        assert st["active"][fp]["failures"] == 3
        recs = obs_flight.recent(kind="serve.quarantine")
        assert recs and recs[-1]["fingerprint"] == fp

    def test_success_resets_streak(self):
        fp = "fp-flaky"
        boom = ValueError("boom")
        quarantine.note_failure(fp, boom)
        quarantine.note_failure(fp, boom)
        quarantine.note_success(fp)
        quarantine.note_failure(fp, boom)
        quarantine.note_failure(fp, boom)
        quarantine.check(fp)  # never hit 3 consecutive: still admitted
        assert quarantine.status()["active"] == {}

    def test_unquarantine_lifts(self):
        boom = ValueError("boom")
        for fp in ("fp-a", "fp-b"):
            for _ in range(3):
                quarantine.note_failure(fp, boom)
        assert len(quarantine.status()["active"]) == 2
        assert tft.unquarantine("fp-a") == 1
        quarantine.check("fp-a")  # admitted again
        with pytest.raises(QueryQuarantined):
            quarantine.check("fp-b")
        assert tft.unquarantine() == 1  # lift everything
        quarantine.check("fp-b")
        assert tracing.counters.get("serve.unquarantined") == 2
        assert tft.quarantine_status()["active"] == {}

    def test_ttl_expires_into_one_probe(self, monkeypatch):
        monkeypatch.setenv("TFT_QUARANTINE_TTL_S", "0.05")
        fp = "fp-ttl"
        boom = ValueError("boom")
        for _ in range(3):
            quarantine.note_failure(fp, boom)
        with pytest.raises(QueryQuarantined):
            quarantine.check(fp)
        time.sleep(0.08)
        quarantine.check(fp)  # the TTL expired: ONE probe admission
        assert tracing.counters.get("serve.quarantine_expired") == 1
        # a still-poisonous plan re-quarantines on the probe's failure
        quarantine.note_failure(fp, boom)
        with pytest.raises(QueryQuarantined):
            quarantine.check(fp)

    def test_disabled_by_knob(self, monkeypatch):
        monkeypatch.setenv("TFT_QUARANTINE_AFTER", "0")
        fp = "fp-off"
        for _ in range(10):
            quarantine.note_failure(fp, ValueError("boom"))
        quarantine.check(fp)
        assert quarantine.status()["active"] == {}

    def test_scheduler_end_to_end(self):
        # a deterministically-failing plan: each run hits a PERMANENT
        # (non-transient) fault at dispatch — fail_n=2 covers the async
        # dispatch AND the pipeline's synchronous re-run of the block,
        # so the classified permanent error surfaces out of the query
        df = tft.frame({"x": np.arange(16.0)}, num_partitions=2)
        with QueryScheduler(quotas={"t": TenantQuota()}, workers=1,
                            name="quar-e2e") as sched:
            for _ in range(3):
                faults.arm("dispatch", 2,
                           message="injected permanent plan bug",
                           transient=False)
                fut = sched.submit(df, _benign_fetches, tenant="t")
                with pytest.raises(InjectedFault):
                    fut.result(timeout=60)
                faults.reset("dispatch")
            # the 4th submission fast-rejects before touching a queue
            with pytest.raises(QueryQuarantined) as ei:
                sched.submit(df, _benign_fetches, tenant="t")
            assert error_kind(ei.value) == "quarantined"
            assert sched.snapshot()["t"]["quarantined"] == 1
            # surfaced in the operator reports
            report = serve.serve_report(scheduler=sched)
            assert "QUARANTINE" in report
            snap = tft.health()
            assert snap["quarantine"]["active"]
            assert any("quarantine" in w for w in snap["warnings"])
            assert "quarantine:" in tft.doctor()
            # lifting re-admits — and with the fault gone the same
            # plan completes on its own merits
            assert tft.unquarantine() == 1
            fut = sched.submit(df, _benign_fetches, tenant="t")
            out = fut.result(timeout=60)
            vals = np.concatenate([np.asarray(b.columns["z"])
                                   for b in out.blocks()])
            np.testing.assert_allclose(np.sort(vals),
                                       np.arange(16.0) * 2.0)

    def test_none_fingerprint_never_quarantined(self):
        for _ in range(10):
            quarantine.note_failure(None, ValueError("boom"))
        quarantine.check(None)
        assert quarantine.status()["active"] == {}


def _benign_fetches(x):
    # module-level so the plan fingerprint is stable across submissions
    return {"z": x * 2.0}


# -- persist artifact checksums --------------------------------------------

class TestPersistChecksums:
    @pytest.fixture(autouse=True)
    def _tier(self, tmp_path):
        prev = _persist.configure(str(tmp_path))
        yield
        _persist.configure(prev)

    def _result_path(self, fp):
        d = os.path.join(_persist.root(), "results")
        names = os.listdir(d)
        assert len(names) == 1
        return os.path.join(d, names[0])

    def test_roundtrip_bit_identical(self):
        blocks = [{"x": np.arange(16.0)}]
        assert _persist.save_result("fp-rt", blocks)
        got = _persist.load_result("fp-rt")
        assert got is not None
        np.testing.assert_array_equal(np.asarray(got[0]["x"]),
                                      blocks[0]["x"])
        assert tracing.counters.get("memory.persist_corrupt") == 0

    def test_bit_rot_detected_and_cold(self):
        # single-bit rot inside a numpy buffer still unpickles — the
        # checksum is the ONLY thing standing between the serving tier
        # and a silently-wrong warm hit
        arr = np.arange(16.0)
        _persist.save_result("fp-rot", [{"x": arr}])
        path = self._result_path("fp-rot")
        with open(path, "rb") as f:
            data = bytearray(f.read())
        # flip one bit INSIDE the serialized float buffer: the file
        # still unpickles cleanly, just to wrong values
        off = data.find(arr.tobytes())
        assert off > 0, "float buffer not found in the artifact"
        data[off + 40] ^= 0x01
        with open(path, "wb") as f:
            f.write(bytes(data))
        # the rotten payload must still be loadable by pickle alone,
        # or this test would only prove what unpickling already catches
        payload = bytes(data[len(_persist._MAGIC)
                             + _persist._DIGEST_LEN:])
        assert pickle.loads(payload) is not None
        assert _persist.load_result("fp-rot") is None
        assert tracing.counters.get("memory.persist_corrupt") == 1
        assert not os.path.exists(path), "corrupt artifact not removed"
        recs = obs_flight.recent(kind="memory.persist_corrupt")
        assert recs and "checksum" in recs[-1]["why"]

    def test_missing_header_cold(self):
        _persist.save_result("fp-hdr", [{"x": np.arange(4.0)}])
        path = self._result_path("fp-hdr")
        with open(path, "wb") as f:
            f.write(b"not a framed artifact")
        assert _persist.load_result("fp-hdr") is None
        assert tracing.counters.get("memory.persist_corrupt") == 1
        assert not os.path.exists(path)

    def test_checksum_ok_unpickle_fails_is_skew_not_rot(self):
        # a valid checksum over an unloadable payload means version/
        # environment skew, not rot: the read_errors path, NOT corrupt
        _persist.save_result("fp-skew", [{"x": np.arange(4.0)}])
        path = self._result_path("fp-skew")
        with open(path, "wb") as f:
            f.write(_persist._pack(b"not-a-pickle"))
        assert _persist.load_result("fp-skew") is None
        assert tracing.counters.get("memory.persist_corrupt") == 0
        assert tracing.counters.get("persist.read_errors") == 1

    def test_disk_fault_read_failure_mode(self):
        _persist.save_result("fp-io", [{"x": np.arange(4.0)}])
        with faults.inject("disk"):
            assert _persist.load_result("fp-io") is None
        assert tracing.counters.get("persist.read_errors") == 1
        assert tracing.counters.get("memory.persist_corrupt") == 0

    def test_disk_fault_corruption_mode(self):
        _persist.save_result("fp-crpt", [{"x": np.arange(4.0)}])
        with faults.inject("disk", message="injected corrupt artifact"):
            assert _persist.load_result("fp-crpt") is None
        assert tracing.counters.get("memory.persist_corrupt") == 1
        assert tracing.counters.get("persist.read_errors") == 0

    def test_checkpoint_artifacts_framed_too(self):
        cp = QueryCheckpoint("q-framed")
        cp.park_stream([np.arange(8.0)], total=2, tag="s")
        loaded = _persist.load_checkpoint("q-framed")
        assert loaded is not None
        d = os.path.join(_persist.root(), "checkpoints")
        path = os.path.join(d, os.listdir(d)[0])
        with open(path, "rb") as f:
            assert f.read(len(_persist._MAGIC)) == _persist._MAGIC


# -- conformance meta-tests ------------------------------------------------

def _classified_kinds():
    from tensorframes_tpu.resilience import classify
    kinds = {"device_lost", "worker_lost", "oom", "transient",
             "permanent"}
    for obj in vars(classify).values():
        if isinstance(obj, type) and issubclass(obj, BaseException):
            kind = getattr(obj, "kind", None)
            if kind:
                kinds.add(kind)
    return kinds


class TestConformance:
    DOCS = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "resilience.md")

    def test_every_error_kind_documented(self):
        with open(self.DOCS) as f:
            text = f.read()
        missing = [k for k in sorted(_classified_kinds())
                   if f"`{k}`" not in text]
        assert not missing, (
            f"classified error kind(s) {missing} have no "
            f"docs/resilience.md entry — every kind the classifier "
            f"can emit needs a documented degradation row")

    def test_every_site_documented(self):
        with open(self.DOCS) as f:
            text = f.read()
        missing = [s for s in sorted(faults.sites()) if s not in text]
        assert not missing, (
            f"fault site(s) {missing} missing from docs/resilience.md "
            f"— the site table and the docs must not drift")

    def test_every_site_driven_by_a_test(self):
        tests_dir = os.path.dirname(__file__)
        corpus = ""
        for name in os.listdir(tests_dir):
            if name.endswith(".py"):
                with open(os.path.join(tests_dir, name)) as f:
                    corpus += f.read()
        undriven = [s for s in sorted(faults.sites())
                    if f'"{s}"' not in corpus and f"'{s}'" not in corpus]
        assert not undriven, (
            f"fault site(s) {undriven} never appear in any test — "
            f"every armed-able site must be driven by >=1 tier-1 test")


# -- the bounded acceptance drill ------------------------------------------

@pytest.mark.invariants
def test_chaos_acceptance_drill(tmp_path):
    """The mixed workload under a seeded >=3-site schedule: bit-identity
    vs the fault-free run, zero leaks, every failure classified, exact
    per-site seed replay. seed=11/rate=0.3 is chosen because it fires
    all four default sites within two rounds (the drill itself asserts
    the rest of the contract and raises on any breach)."""
    report = chaos_soak.run_drill(seed=11, rate=0.3, rounds=2,
                                  persist_dir=str(tmp_path))
    fired_sites = {site for site, _ in report["firings"]}
    assert {"device", "worker", "disk"} <= fired_sites, (
        f"drill must fire the device+worker+disk minimum; "
        f"got {sorted(fired_sites)}")
    assert report["fired"] >= 3
    assert tracing.counters.get("chaos.fired") >= report["fired"]


@pytest.mark.slow
@pytest.mark.invariants
def test_chaos_soak_slow(tmp_path):
    """More rounds of the same drill (the standalone soak's code path),
    at a different seed so the suite covers two schedules."""
    report = chaos_soak.run_drill(seed=7, rate=0.25, rounds=6,
                                  persist_dir=str(tmp_path))
    assert report["fired"] >= 3
