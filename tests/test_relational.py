"""Relational half: joins + sketch aggregates (``tensorframes_tpu/relational/``).

The acceptance spine (ISSUE 12 / ROADMAP item 4):

- broadcast hash join and sort-merge join are BIT-IDENTICAL to a
  numpy/pandas-free host oracle across the equivalence suite —
  inner/left, empty sides, duplicate keys, string ride-alongs,
  filter-to-zero — including under an injected ``device:1`` loss
  (sort-merge rides dsort's elastic recovery) and a 4x-over-budget
  build side routed through the memory ledger (chunked probe);
- sketch combiners (HLL / DDSketch quantile / Misra–Gries top-k) pass
  their error-bound suites when folded through ``aggregate``,
  ``daggregate``, and a windowed stream — and the HLL/quantile states
  are bit-identical across all three paths;
- ``ParquetScanNode`` predicate pushdown skips refuted row groups at
  the footer (bytes-touched asserted) while staying bit-identical to
  ``TFT_FUSE=0``;
- ``frame.hot_keys()`` surfaces the PR 7 salting observations.

No deadline-sensitive assertions here — nothing needs the ``timing``
marker.
"""

import os

import jax
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import memory as tmem
from tensorframes_tpu import parallel as par
from tensorframes_tpu import relational as rel
from tensorframes_tpu.engine.ops import (InputNotFoundError,
                                         InvalidTypeError)
from tensorframes_tpu.parallel import distributed as pdist
from tensorframes_tpu.parallel import elastic
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils.tracing import counters


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return par.local_mesh(8)


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    yield
    faults.reset()
    tmem._reset()


def _snap(key):
    return counters.snapshot().get(key, 0)


# ---------------------------------------------------------------------------
# the host oracle (plain python dicts — no numpy tricks, no pandas)
# ---------------------------------------------------------------------------

def oracle_join(left_rows, right_rows, left_names, right_names, on,
                how, right_fields, indicator=None):
    """Reference join: probe order preserved, matches in build-row
    order, left-join fill = NaN/0/'' by dtype kind."""
    on = [on] if isinstance(on, str) else list(on)
    l_on = [left_names.index(k) for k in on]
    r_on = [right_names.index(k) for k in on]
    r_val_idx = [i for i, n in enumerate(right_names) if n not in on]
    table = {}
    for r in right_rows:
        table.setdefault(tuple(r[i] for i in r_on), []).append(
            tuple(r[i] for i in r_val_idx))
    fills = []
    for i in r_val_idx:
        f = right_fields[i]
        kind = np.dtype(f.dtype.np_storage).kind
        fills.append(np.nan if kind == "f" else
                     (False if kind == "b" else
                      (0 if kind in "iu" else "")))
    out = []
    for row in left_rows:
        key = tuple(row[i] for i in l_on)
        matches = table.get(key, [])
        if matches:
            for m in matches:
                out.append(tuple(row) + m
                           + ((1,) if indicator else ()))
        elif how == "left":
            out.append(tuple(row) + tuple(fills)
                       + ((0,) if indicator else ()))
    return out


def _rows(df):
    return [tuple(r) for r in df.collect()]


def _eq(a, b):
    """Tuple-row equality with NaN == NaN (the left-join fill)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float) \
                    and np.isnan(x) and np.isnan(y):
                continue
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                if not np.array_equal(np.asarray(x), np.asarray(y)):
                    return False
                continue
            if x != y:
                return False
    return True


def _left_frame(parts=3):
    return tft.frame(
        {"k": np.array([1, 2, 3, 4, 2, 9, 5, 2], np.int64),
         "v": np.array([10., 20., 30., 40., 21., 90., 50., 22.]),
         "tag": np.array(list("abcdefgh"), object)},
        num_partitions=parts)


def _right_unique():
    return tft.frame(
        {"k": np.array([2, 3, 5], np.int64),
         "w": np.array([200., 300., 500.]),
         "name": np.array(["two", "three", "five"], object)})


def _right_dup():
    return tft.frame(
        {"k": np.array([2, 2, 3, 7], np.int64),
         "w": np.array([200., 201., 300., 700.]),
         "name": np.array(["two", "two'", "three", "seven"], object)})


def _oracle_for(left, right, on, how, indicator=None):
    return oracle_join(_rows(left), _rows(right), left.schema.names,
                       right.schema.names, on, how,
                       list(right.schema), indicator=indicator)


# ---------------------------------------------------------------------------
# broadcast hash join: CPU-oracle equivalence suite
# ---------------------------------------------------------------------------

@pytest.mark.join
class TestBroadcastJoin:
    @pytest.mark.parametrize("how", ["inner", "left"])
    @pytest.mark.parametrize("dup", [False, True])
    def test_oracle_equivalence(self, how, dup):
        left = _left_frame()
        right = _right_dup() if dup else _right_unique()
        out = rel.broadcast_join(left, right, on="k", how=how)
        assert _eq(_rows(out), _oracle_for(left, right, "k", how))

    def test_indicator_column(self):
        left, right = _left_frame(), _right_unique()
        out = rel.broadcast_join(left, right, on="k", how="left",
                                 indicator="matched")
        assert out.schema.names[-1] == "matched"
        assert _eq(_rows(out),
                   _oracle_for(left, right, "k", "left",
                               indicator="matched"))

    def test_empty_left(self):
        left = tft.frame({"k": np.empty(0, np.int64),
                          "v": np.empty(0)})
        out = rel.broadcast_join(left, _right_unique(), on="k",
                                 how="left")
        assert out.count() == 0
        assert out.schema.names == ["k", "v", "w", "name"]

    @pytest.mark.parametrize("how", ["inner", "left"])
    def test_empty_right(self, how):
        left = _left_frame()
        right = tft.frame({"k": np.empty(0, np.int64),
                           "w": np.empty(0)})
        out = rel.broadcast_join(left, right, on="k", how=how)
        assert _eq(_rows(out), _oracle_for(left, right, "k", how))

    def test_filter_to_zero_probe(self):
        left = _left_frame().filter(lambda k: k > 100)
        out = rel.broadcast_join(left, _right_unique(), on="k",
                                 how="inner")
        assert out.count() == 0
        assert out.schema.names == ["k", "v", "tag", "w", "name"]

    def test_multi_key_and_strings(self):
        left = tft.frame(
            {"a": np.array([1, 1, 2, 2], np.int64),
             "s": np.array(["x", "y", "x", "z"], object),
             "v": np.arange(4.0)})
        right = tft.frame(
            {"a": np.array([1, 2], np.int64),
             "s": np.array(["y", "x"], object),
             "w": np.array([7.0, 8.0])})
        for how in ("inner", "left"):
            out = rel.broadcast_join(left, right, on=["a", "s"],
                                     how=how)
            assert _eq(_rows(out),
                       _oracle_for(left, right, ["a", "s"], how))

    def test_block_boundaries_preserved(self):
        left = _left_frame(parts=4)
        out = rel.broadcast_join(left, _right_unique(), on="k",
                                 how="left")
        assert [b.num_rows for b in out.blocks()] == \
            [b.num_rows for b in left.blocks()]

    def test_vector_cells_ride_along(self):
        right = tft.frame(
            {"k": np.array([2, 3], np.int64),
             "emb": np.arange(6.0).reshape(2, 3)})
        left = tft.frame({"k": np.array([3, 1, 2], np.int64)})
        out = rel.broadcast_join(left, right, on="k", how="left")
        got = {int(r[0]): np.asarray(r[1]) for r in out.collect()}
        assert np.array_equal(got[3], [3., 4., 5.])
        assert np.array_equal(got[2], [0., 1., 2.])
        assert np.all(np.isnan(got[1]))

    def test_tensorframe_join_method(self):
        # the public sugar must route to the same implementation
        left, right = _left_frame(), _right_unique()
        out = left.join(right, on="k", how="left")
        assert _eq(_rows(out), _oracle_for(left, right, "k", "left"))

    def test_validation_errors(self):
        left, right = _left_frame(), _right_unique()
        with pytest.raises(InputNotFoundError):
            rel.broadcast_join(left, right, on="nope")
        with pytest.raises(ValueError, match="duplicate column"):
            rel.broadcast_join(
                left, tft.frame({"k": np.array([1], np.int64),
                                 "v": np.array([1.0])}), on="k")
        with pytest.raises(ValueError, match="inner.*left|how"):
            rel.broadcast_join(left, right, on="k", how="outer")

    def test_plan_node_estimates_and_admission(self):
        left, right = _left_frame(), _right_unique()
        out = rel.broadcast_join(left, right, on="k", how="left")
        assert out.estimated_rows() == left.count()
        assert out.estimated_bytes() is not None \
            and out.estimated_bytes() > 0

    def test_downstream_fusion_and_pruning(self):
        import jax.numpy as jnp
        left, right = _left_frame(), _right_unique()
        out = rel.broadcast_join(left, right, on="k", how="left")
        chain = out.map_blocks(
            lambda v, w: {"z": v + jnp.nan_to_num(w)}).select(
            ["k", "z"])
        expect = [(int(r[0]),
                   float(r[1]) + (0.0 if np.isnan(r[3]) else r[3]))
                  for r in out.collect()]
        got = _rows(chain)
        assert got == expect
        info = "\n".join(chain._plan_info or [])
        # pruning reached INTO the join: tag/name never materialized
        assert "join[broadcast,left]" in info
        assert "'tag'" in info and "pruned" in info


# ---------------------------------------------------------------------------
# sort-merge join
# ---------------------------------------------------------------------------

def _smj_oracle(left, right, on, how, indicator=None):
    """Sort-merge oracle: the broadcast oracle over the key-sorted
    (stable) left side."""
    on_l = [on] if isinstance(on, str) else list(on)
    lrows = _rows(left)
    idx = [left.schema.names.index(k) for k in on_l]
    lrows = sorted(lrows, key=lambda r: tuple(r[i] for i in idx))
    rrows = _rows(right)
    ridx = [right.schema.names.index(k) for k in on_l]
    rrows = sorted(rrows, key=lambda r: tuple(r[i] for i in ridx))
    return oracle_join(lrows, rrows, left.schema.names,
                       right.schema.names, on_l, how,
                       list(right.schema), indicator=indicator)


@pytest.mark.join
class TestSortMergeJoin:
    @pytest.mark.parametrize("how", ["inner", "left"])
    @pytest.mark.parametrize("dup", [False, True])
    def test_host_oracle(self, how, dup):
        left = _left_frame()
        right = _right_dup() if dup else _right_unique()
        out = rel.sort_merge_join(left, right, on="k", how=how)
        assert _eq(_rows(out), _smj_oracle(left, right, "k", how))

    def test_mesh_equals_host(self, mesh8):
        rng = np.random.default_rng(7)
        left = tft.frame({"k": rng.integers(0, 20, 64).astype(np.int64),
                          "v": np.arange(64.0),
                          "s": np.array([f"r{i}" for i in range(64)],
                                        object)}, num_partitions=4)
        right = tft.frame(
            {"k": rng.integers(0, 20, 40).astype(np.int64),
             "w": np.arange(40.0)}, num_partitions=2)
        host = rel.sort_merge_join(left, right, on="k", how="inner")
        mesh = rel.sort_merge_join(left, right, on="k", how="inner",
                                   mesh=mesh8)
        assert _eq(_rows(mesh), _rows(host))
        assert _eq(_rows(mesh), _smj_oracle(left, right, "k", "inner"))

    def test_device_loss_bit_identical(self, mesh8):
        # the acceptance drive: an injected device:1 loss mid-dsort
        # shrinks/reshards/re-runs; the join result must not change
        rng = np.random.default_rng(8)
        left = tft.frame({"k": rng.integers(0, 10, 64).astype(np.int64),
                          "v": np.arange(64, dtype=np.int64)},
                         num_partitions=4)
        right = tft.frame(
            {"k": rng.integers(0, 10, 32).astype(np.int64),
             "w": np.arange(32, dtype=np.int64)})
        healthy = _rows(rel.sort_merge_join(left, right, on="k",
                                            how="left", mesh=mesh8))
        lost0 = _snap("mesh.devices_lost")
        with faults.inject("device", 1):
            wounded = _rows(rel.sort_merge_join(left, right, on="k",
                                                how="left", mesh=mesh8))
        assert _snap("mesh.devices_lost") > lost0
        assert _eq(wounded, healthy)

    def test_ledger_routes_external_sort(self, mesh8):
        # a 4x-over-budget side must go through the external-sort path
        # and still match the host oracle bit for bit
        n = 4096
        rng = np.random.default_rng(9)
        left = tft.frame({"k": rng.integers(0, 64, n).astype(np.int64),
                          "v": np.arange(n, dtype=np.int64)},
                         num_partitions=4)
        right = tft.frame(
            {"k": np.arange(64, dtype=np.int64),
             "w": np.arange(64, dtype=np.int64)})
        oracle = _smj_oracle(left, right, "k", "inner")
        tmem.configure(limit_bytes=int(n * 16 // 4))  # ~4x over
        spills0 = _snap("memory.spills")
        out = rel.sort_merge_join(left, right, on="k", how="inner",
                                  mesh=mesh8)
        assert _eq(_rows(out), oracle)
        tmem._reset()

    @pytest.mark.parametrize("how", ["inner", "left"])
    def test_empty_sides(self, how):
        full = tft.frame({"k": np.array([1, 2], np.int64),
                          "v": np.array([1., 2.])})
        empty = tft.frame({"k": np.empty(0, np.int64),
                           "w": np.empty(0)})
        out = rel.sort_merge_join(full, empty, on="k", how=how)
        assert _eq(_rows(out), _smj_oracle(full, empty, "k", how))
        out2 = rel.sort_merge_join(
            tft.frame({"k": np.empty(0, np.int64),
                       "v": np.empty(0)}),
            tft.frame({"k": np.array([1], np.int64),
                       "w": np.array([1.])}), on="k", how=how)
        assert out2.count() == 0

    def test_string_key_rejected(self):
        left = tft.frame({"k": np.array(["a"], object),
                          "v": np.array([1.0])})
        with pytest.raises(InvalidTypeError):
            rel.sort_merge_join(left, left.select(["k"]), on="k")

    def test_auto_routing_string_keys_stay_broadcast(self, mesh8,
                                                     monkeypatch):
        # auto strategy must never pick sort-merge for a query only
        # broadcast can run (string keys), whatever the size estimate
        monkeypatch.setenv("TFT_BROADCAST_LIMIT_BYTES", "1")
        left = tft.frame({"k": np.array(["a", "b"], object),
                          "v": np.array([1.0, 2.0])})
        right = tft.frame({"k": np.array(["b"], object),
                           "w": np.array([9.0])})
        out = rel.join(left, right, on="k", how="left", mesh=mesh8)
        assert _eq(_rows(out), _oracle_for(left, right, "k", "left"))


# ---------------------------------------------------------------------------
# the ledger-chunked broadcast build (4x over budget)
# ---------------------------------------------------------------------------

@pytest.mark.join
@pytest.mark.memory
class TestChunkedBuild:
    def test_over_budget_build_bit_identical(self):
        n = 20000
        right = tft.frame({"k": np.arange(n, dtype=np.int64),
                           "w": np.arange(n, dtype=np.float64),
                           "w2": np.arange(n, dtype=np.float64)})
        left = tft.frame(
            {"k": np.array([0, 5, n - 1, n + 7, 123], np.int64)},
            num_partitions=2)
        unlimited = _rows(rel.broadcast_join(left, right, on="k",
                                             how="left"))
        budget = int(n * 16 // 4)  # build tensor bytes ~4x the budget
        tmem.configure(limit_bytes=budget)
        c0 = _snap("relational.build_chunks")
        out = rel.broadcast_join(left, right, on="k", how="left")
        got = _rows(out)
        assert _snap("relational.build_chunks") - c0 >= 2
        assert _eq(got, unlimited)
        assert _eq(got, _oracle_for(left, right, "k", "left"))
        tmem._reset()


# ---------------------------------------------------------------------------
# streaming enrichment
# ---------------------------------------------------------------------------

@pytest.mark.join
@pytest.mark.stream
class TestStreamJoin:
    def test_stream_enrich_equals_batch(self):
        import tensorframes_tpu.stream as stream
        rng = np.random.default_rng(3)
        batches = [{"k": rng.integers(0, 4, 50).astype(np.int64),
                    "x": rng.normal(0, 1, 50)} for _ in range(3)]
        table = tft.frame(
            {"k": np.array([0, 1, 2], np.int64),
             "label": np.array(["a", "b", "c"], object),
             "w": np.array([0.5, 1.5, 2.5])})
        sf = stream.from_source(
            stream.GeneratorSource(iter(batches))).join(table, on="k")
        h = sf.start()
        h.run()
        got = [_rows(f) for f in h.collect_updates()]
        expect = [_rows(rel.broadcast_join(tft.frame(dict(b)), table,
                                           on="k", how="left"))
                  for b in batches]
        assert len(got) == len(expect)
        for g, e in zip(got, expect):
            assert _eq(g, e)

    def test_definition_time_validation(self):
        import tensorframes_tpu.stream as stream
        src = stream.GeneratorSource(
            iter([{"k": np.array([1], np.int64)}]),
            schema=tft.frame({"k": np.array([1], np.int64)}).schema)
        table = tft.frame({"j": np.array([1], np.int64)})
        with pytest.raises(InputNotFoundError):
            stream.from_source(src).join(table, on="k")


# ---------------------------------------------------------------------------
# sketches: error bounds + cross-path bit-identity
# ---------------------------------------------------------------------------

def _sketch_data(n=12000, groups=3, seed=5):
    rng = np.random.default_rng(seed)
    return {"g": rng.integers(0, groups, n).astype(np.int64),
            "x": rng.lognormal(0.0, 1.5, n),
            "it": rng.integers(0, 500, n).astype(np.int64)}


@pytest.mark.sketch
class TestSketchAggregate:
    def test_hll_error_bound(self):
        cols = _sketch_data()
        df = tft.frame(cols, num_partitions=4)
        sk = rel.approx_distinct(bits=10)
        out = tft.aggregate({"it": sk}, df.group_by("g"))
        # 5-sigma envelope on the classic 1.04/sqrt(m) bound
        bound = 5 * sk.relative_error
        for r in out.collect():
            true = len(np.unique(cols["it"][cols["g"] == r[0]]))
            assert abs(int(r[1]) - true) <= max(2, bound * true)

    def test_quantile_error_bound(self):
        cols = _sketch_data()
        df = tft.frame(cols, num_partitions=4)
        sk = rel.approx_quantile(qs=(0.1, 0.5, 0.9), alpha=0.02)
        out = tft.aggregate({"x": sk}, df.group_by("g"))
        for r in out.collect():
            vals = cols["x"][cols["g"] == r[0]]
            for j, q in enumerate(sk.qs):
                true = np.quantile(vals, q, method="inverted_cdf")
                got = np.asarray(r[1])[j]
                assert abs(got - true) <= sk.relative_error * abs(true)

    def test_quantile_negative_and_zero(self):
        vals = np.array([-100.0, -1.0, 0.0, 0.0, 1.0, 100.0])
        df = tft.frame({"g": np.zeros(6, np.int64), "x": vals})
        sk = rel.approx_quantile(qs=0.5, alpha=0.01, min_value=1e-3,
                                 max_value=1e3)
        out = tft.aggregate({"x": sk}, df.group_by("g"))
        got = out.collect()[0][1]
        assert got == 0.0  # the exact zero bucket

    def test_quantile_nan_rows_dropped(self):
        vals = np.array([np.nan, np.nan, np.nan, 10.0, 20.0, 30.0])
        df = tft.frame({"g": np.zeros(6, np.int64), "x": vals})
        sk = rel.approx_quantile(qs=0.5, alpha=0.01, min_value=1e-3,
                                 max_value=1e3)
        got = tft.aggregate({"x": sk}, df.group_by("g")).collect()[0][1]
        assert abs(got - 20.0) <= sk.relative_error * 20.0

    def test_topk_exactness_above_threshold(self):
        rng = np.random.default_rng(11)
        n = 10000
        heavy = np.concatenate([np.full(4000, 77), np.full(2500, 13)])
        noise = rng.integers(1000, 9000, n - len(heavy))
        it = np.concatenate([heavy, noise]).astype(np.int64)
        rng.shuffle(it)
        df = tft.frame({"g": np.zeros(n, np.int64), "it": it},
                       num_partitions=5)
        sk = rel.approx_top_k(k=8)
        out = tft.aggregate({"it": sk}, df.group_by("g"))
        items = list(np.asarray(out.collect()[0][1]))
        cts = dict(zip(items, np.asarray(out.collect()[0][2])))
        # Misra–Gries guarantee: every item above n/(k+1) survives,
        # counts under-estimate by at most n/(k+1)
        thr = n / (sk.k + 1)
        for item, true in ((77, 4000), (13, 2500)):
            assert item in items
            assert true - thr <= cts[item] <= true

    def test_mixed_scalar_and_sketch(self):
        cols = _sketch_data(n=4000)
        df = tft.frame(cols, num_partitions=3)
        out = tft.aggregate({"x": "sum",
                             "it": rel.approx_distinct(bits=8)},
                            df.group_by("g"))
        assert out.schema.names == ["g", "it", "x"]
        for r in out.collect():
            m = cols["g"] == r[0]
            np.testing.assert_allclose(r[2], cols["x"][m].sum(),
                                       rtol=1e-9)

    def test_strings_distinct(self):
        names = np.array([f"u{i % 37}" for i in range(500)], object)
        df = tft.frame({"g": np.zeros(500, np.int64), "s": names})
        out = tft.aggregate({"s": rel.approx_distinct(bits=10)},
                            df.group_by("g"))
        assert abs(int(out.collect()[0][1]) - 37) <= 4

    def test_validation(self):
        df = tft.frame({"g": np.zeros(4, np.int64),
                        "x": np.arange(4.0)})
        with pytest.raises(ValueError, match="integer"):
            tft.aggregate({"x": rel.approx_top_k(4)}, df.group_by("g"))
        with pytest.raises(InputNotFoundError):
            tft.aggregate({"nope": rel.approx_distinct()},
                          df.group_by("g"))

    def test_bfloat16_hashes_distinct(self):
        ml_dtypes = pytest.importorskip("ml_dtypes")
        from tensorframes_tpu.relational.sketch import _hash64
        a = np.array([0.25, 0.5, 0.75], dtype=ml_dtypes.bfloat16)
        hashes = _hash64(a)
        assert len(set(hashes.tolist())) == 3  # not int-truncated

    def test_bfloat16_fill_is_nan(self):
        from tensorframes_tpu.relational.join import _fill_value

        class _F:
            class dtype:
                np_storage = None
        ml_dtypes = pytest.importorskip("ml_dtypes")
        f = _F()
        f.dtype = type("D", (), {"np_storage":
                                 np.dtype(ml_dtypes.bfloat16)})
        assert np.isnan(_fill_value(f))


@pytest.mark.sketch
class TestSketchDaggregate:
    def test_bit_identical_to_host_aggregate(self, mesh8):
        cols = _sketch_data(n=8000)
        df = tft.frame(cols, num_partitions=4)
        fetches = {"x": rel.approx_quantile(qs=0.5, alpha=0.02),
                   "it": rel.approx_distinct(bits=10)}
        host = sorted(_rows(tft.aggregate(fetches, df.group_by("g"))))
        dist = pdist.distribute(df, mesh8)
        mesh = sorted(_rows(pdist.daggregate(fetches, dist, "g")))
        assert host == mesh

    def test_mixed_with_scalar_collective(self, mesh8):
        cols = _sketch_data(n=4096)
        df = tft.frame(cols, num_partitions=4)
        dist = pdist.distribute(df, mesh8)
        out = pdist.daggregate(
            {"it": rel.approx_top_k(k=6), "x": "sum"}, dist, "g")
        assert out.schema.names == ["g", "it", "it_counts", "x"]
        for r in out.collect():
            m = cols["g"] == r[0]
            np.testing.assert_allclose(
                r[3], cols["x"][m].sum(), rtol=1e-9)
            # the modal item of each group must survive
            vals, cts = np.unique(cols["it"][m], return_counts=True)

    def test_max_groups_rejected_with_sketches(self, mesh8):
        df = tft.frame({"g": np.zeros(64, np.int64),
                        "it": np.arange(64, dtype=np.int64)})
        dist = pdist.distribute(df, mesh8)
        with pytest.raises(ValueError, match="max_groups"):
            pdist.daggregate({"it": rel.approx_distinct()}, dist, "g",
                             max_groups=8)

    def test_elastic_recovery(self, mesh8):
        cols = _sketch_data(n=4096)
        df = tft.frame(cols, num_partitions=4)
        dist = pdist.distribute(df, mesh8)
        fetches = {"it": rel.approx_distinct(bits=10)}
        healthy = sorted(_rows(pdist.daggregate(fetches, dist, "g")))
        dist2 = pdist.distribute(df, mesh8)
        with faults.inject("device", 1):
            wounded = sorted(_rows(pdist.daggregate(fetches, dist2,
                                                    "g")))
        assert wounded == healthy


@pytest.mark.sketch
@pytest.mark.stream
class TestSketchStream:
    def test_windowed_stream_equals_batch(self):
        import tensorframes_tpu.stream as stream
        rng = np.random.default_rng(21)
        batches = [{"t": np.full(400, float(i)),
                    "k": rng.integers(0, 2, 400).astype(np.int64),
                    "x": rng.lognormal(0, 1, 400),
                    "it": rng.integers(0, 100, 400).astype(np.int64)}
                   for i in range(6)]
        fetches = {"x": rel.approx_quantile(qs=0.5, alpha=0.02),
                   "it": rel.approx_distinct(bits=9)}
        sf = stream.from_source(stream.GeneratorSource(iter(batches)))
        agg = sf.group_by("k").aggregate(
            fetches, window=stream.tumbling(2.0), time_col="t")
        h = agg.start()
        h.run()
        frames = h.collect_updates()
        assert len(frames) == 3
        for wi, f in enumerate(frames):
            t0 = wi * 2.0
            allc = {k: np.concatenate([b[k] for b in batches])
                    for k in batches[0]}
            m = (allc["t"] >= t0) & (allc["t"] < t0 + 2.0)
            bdf = tft.frame({"k": allc["k"][m], "x": allc["x"][m],
                             "it": allc["it"][m]})
            batch = sorted(_rows(tft.aggregate(fetches,
                                               bdf.group_by("k"))))
            got = sorted(tuple(r)[1:] for r in f.collect())
            assert got == batch

    def test_streaming_topk_host_state(self):
        import tensorframes_tpu.stream as stream
        batches = [{"t": np.full(100, float(i)),
                    "k": np.zeros(100, np.int64),
                    "it": np.where(np.arange(100) < 60, 5,
                                   np.arange(100)).astype(np.int64)}
                   for i in range(4)]
        sf = stream.from_source(stream.GeneratorSource(iter(batches)))
        agg = sf.group_by("k").aggregate(
            {"it": rel.approx_top_k(k=4)},
            window=stream.tumbling(4.0), time_col="t")
        h = agg.start()
        h.run()
        frames = h.collect_updates()
        assert len(frames) == 1
        row = frames[0].collect()[0]
        items = list(np.asarray(row[2]))
        assert 5 in items  # 240/400 rows: far above the n/(k+1) bar
        # host-merged sketch state costs zero device bytes
        assert agg.state_rows == 0  # everything emitted at finalize


# ---------------------------------------------------------------------------
# parquet predicate pushdown (ROADMAP 2c satellite)
# ---------------------------------------------------------------------------

def _write_grouped_parquet(tmp_path, groups=4, rows=64):
    import pyarrow.parquet as pq

    from tensorframes_tpu.io import _frame_block_to_table
    path = str(tmp_path / "push.parquet")
    writer = None
    for i in range(groups):
        p = tft.frame({
            "x": np.arange(rows, dtype=np.float64) + i * rows,
            "y": np.full(rows, i, np.int64),
            "z": np.arange(rows, dtype=np.float64)})
        tbl = _frame_block_to_table(p.blocks()[0], p.schema)
        if writer is None:
            writer = pq.ParquetWriter(path, tbl.schema)
        writer.write_table(tbl)
    writer.close()
    return path


@pytest.mark.join
@pytest.mark.plan
class TestParquetPushdown:
    def test_skips_refuted_groups_bytes_counted(self, tmp_path):
        path = _write_grouped_parquet(tmp_path)
        df = tft.io.read_parquet(path)
        g0 = _snap("plan.pushdown_groups_skipped")
        b0 = _snap("plan.pushdown_bytes_skipped")
        out = df.filter(lambda x: x > 160.0).map_blocks(
            lambda x, z: {"s": x + z})
        rows = _rows(out)
        assert _snap("plan.pushdown_groups_skipped") - g0 == 2
        skipped = _snap("plan.pushdown_bytes_skipped") - b0
        assert skipped > 0  # footer-accounted bytes never read
        # bit-identity vs the unfused path (which reads everything)
        os.environ["TFT_FUSE"] = "0"
        try:
            df2 = tft.io.read_parquet(path)
            out2 = df2.filter(lambda x: x > 160.0).map_blocks(
                lambda x, z: {"s": x + z})
            assert _rows(out2) == rows
            assert [b.num_rows for b in out.blocks()] == \
                [b.num_rows for b in out2.blocks()]
        finally:
            del os.environ["TFT_FUSE"]

    def test_conjunction_and_int_atoms(self, tmp_path):
        path = _write_grouped_parquet(tmp_path)
        df = tft.io.read_parquet(path)
        out = df.filter(lambda x, y: (x > 100.0) & (y <= 2)).select(
            ["x", "y"])
        rows = _rows(out)
        raw = _rows(tft.io.read_parquet(path).select(["x", "y"]))
        expect = [r for r in raw if r[0] > 100.0 and r[1] <= 2]
        assert rows == expect

    def test_int_column_fractional_literal_not_truncated(self,
                                                         tmp_path):
        # x < 3.5 over an int group holding 3 must NOT be refuted (a
        # literal truncated into the int dtype would wrongly skip it);
        # a beyond-2**53 literal must never refute anything
        import pyarrow.parquet as pq

        from tensorframes_tpu.io import _frame_block_to_table
        path = str(tmp_path / "ints.parquet")
        writer = None
        for base in (0, 100):
            p = tft.frame({"x": np.arange(10, dtype=np.int64) + base})
            tbl = _frame_block_to_table(p.blocks()[0], p.schema)
            if writer is None:
                writer = pq.ParquetWriter(path, tbl.schema)
            writer.write_table(tbl)
        writer.close()
        df = tft.io.read_parquet(path)
        out = df.filter(lambda x: x < 3.5).map_blocks(
            lambda x: {"s": x * 2})
        assert sorted(r[0] for r in out.collect()) == [0, 1, 2, 3]
        out2 = tft.io.read_parquet(path).filter(
            lambda x: x < 1e20).map_blocks(lambda x: {"s": x * 2})
        assert out2.count() == 20

    def test_value_changing_cast_blocks_pushdown(self, tmp_path):
        # a truncating cast inside the predicate changes what the
        # device compares: trunc(-4.5) >= -4 keeps rows whose raw x
        # stats would refute x >= -4 — the atom must not be emitted
        import jax.numpy as jnp
        import pyarrow.parquet as pq

        from tensorframes_tpu.io import _frame_block_to_table
        path = str(tmp_path / "cast.parquet")
        writer = None
        for lo in (-4.9, 10.0):
            p = tft.frame({"x": np.linspace(lo, lo + 0.8, 8)})
            tbl = _frame_block_to_table(p.blocks()[0], p.schema)
            if writer is None:
                writer = pq.ParquetWriter(path, tbl.schema)
            writer.write_table(tbl)
        writer.close()

        def pred(x):
            return x.astype(jnp.int32) >= -4

        fused = _rows(tft.io.read_parquet(path).filter(pred)
                      .map_blocks(lambda x: {"s": x * 2}))
        os.environ["TFT_FUSE"] = "0"
        try:
            perop = _rows(tft.io.read_parquet(path).filter(pred)
                          .map_blocks(lambda x: {"s": x * 2}))
        finally:
            del os.environ["TFT_FUSE"]
        assert fused == perop
        assert len(fused) == 16  # trunc keeps every row of both groups

    def test_unextractable_predicate_reads_everything(self, tmp_path):
        path = _write_grouped_parquet(tmp_path)
        df = tft.io.read_parquet(path)
        g0 = _snap("plan.pushdown_groups_skipped")
        out = df.filter(lambda x, z: (x - z) > 1e9).map_blocks(
            lambda x: {"s": x * 2})
        assert out.count() == 0
        assert _snap("plan.pushdown_groups_skipped") == g0

    def test_explicit_partitions_push_down_and_remap(self, tmp_path):
        # the recorded PR 12 follow-on, closed in PR 13: an explicitly
        # re-partitioned scan refutes per row group and remaps the
        # surviving rows onto the partition spans the unpushed read
        # would have produced — bit-identical incl. block boundaries
        path = _write_grouped_parquet(tmp_path)
        for parts in (3, 5, 7):
            df = tft.io.read_parquet(path, num_partitions=parts)
            g0 = _snap("plan.pushdown_groups_skipped")
            out = df.filter(lambda x: x > 160.0).map_blocks(
                lambda x: {"s": x * 2})
            rows = _rows(out)
            assert out.count() == 95  # x in 161..255
            assert _snap("plan.pushdown_groups_skipped") - g0 == 2
            os.environ["TFT_FUSE"] = "0"
            try:
                out2 = tft.io.read_parquet(
                    path, num_partitions=parts).filter(
                    lambda x: x > 160.0).map_blocks(
                    lambda x: {"s": x * 2})
                assert _rows(out2) == rows
                assert [b.num_rows for b in out.blocks()] == \
                    [b.num_rows for b in out2.blocks()]
            finally:
                del os.environ["TFT_FUSE"]

    def test_more_partitions_than_rows_remap(self, tmp_path):
        # degenerate split: _split_even caps partitions at the TOTAL
        # row count (refuted groups included), matching the unpushed
        # partition structure exactly
        path = _write_grouped_parquet(tmp_path, groups=2, rows=4)
        df = tft.io.read_parquet(path, num_partitions=6)
        out = df.filter(lambda x: x >= 4.0).map_blocks(
            lambda x: {"s": x * 2})
        rows = _rows(out)
        os.environ["TFT_FUSE"] = "0"
        try:
            out2 = tft.io.read_parquet(path, num_partitions=6).filter(
                lambda x: x >= 4.0).map_blocks(lambda x: {"s": x * 2})
            assert _rows(out2) == rows
            assert [b.num_rows for b in out.blocks()] == \
                [b.num_rows for b in out2.blocks()]
        finally:
            del os.environ["TFT_FUSE"]


# ---------------------------------------------------------------------------
# hot-key observations (PR 7 surfacing satellite)
# ---------------------------------------------------------------------------

@pytest.mark.join
class TestHotKeys:
    def test_hot_keys_surface_and_explain(self, mesh8, monkeypatch):
        monkeypatch.setenv("TFT_HOT_KEY_FRACTION", "0.5")
        rng = np.random.default_rng(13)
        k = np.concatenate([np.full(800, 7),
                            rng.integers(0, 5, 200)]).astype(np.int64)
        df = tft.frame({"k": k, "v": np.arange(1000, dtype=np.int64)})
        dist = pdist.distribute(df, mesh8)
        out = pdist.daggregate({"v": "sum"}, dist, "k")
        hot = out.hot_keys()
        assert len(hot) == 1
        assert hot[0]["keys"] == {"k": 7}
        assert 0.7 <= hot[0]["fraction"] <= 0.9
        assert hot[0]["salt_slots"] == 8
        report = out.explain()
        assert "hot key" in report and "k=7" in report

    def test_no_salting_no_hot_keys(self):
        df = tft.frame({"k": np.arange(20, dtype=np.int64),
                        "v": np.arange(20.0)})
        out = tft.aggregate({"v": "sum"}, df.group_by("k"))
        assert out.hot_keys() == []
