"""Worker for the 2-process cluster test (spawned by test_cluster.py).

Each process contributes DIFFERENT local rows (uneven counts, forcing
per-process padding), then runs the full distributed surface —
dmap_blocks, monoid + generic dreduce_blocks, monoid + generic
daggregate, collect — and asserts parity against a numpy recomputation of
the GLOBAL data on every process. The reference ran this shape of test as
driver + executor JVMs over Spark RPC (``DebugRowOps.scala:372-386``);
here both processes run the same SPMD program.

Usage: python tests/cluster_worker.py <process_id> <num_processes> <port>
"""

import os
import sys

# the step matrix, importable by test_cluster.py WITHOUT duplicating it
# (one source of truth; main() asserts its table matches)
STEP_NAMES = [
    "dmap",
    "dreduce_monoid",
    "dreduce_generic",
    "daggregate_monoid",
    "daggregate_generic",
    "daggregate_device_keys",
    "dfilter",
    "dsort",
    "daggregate_composite_keys",
    "checkpoint_resume",
]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "")
     + " --xla_force_host_platform_device_count=4").strip())

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    """Run the step matrix; each step reports its own pass/fail marker.

    ``[worker N] STEP <name> OK`` / ``... STEP <name> FAIL`` + traceback —
    the driver-side test file turns each marker into its own pytest test,
    so a failure names the op instead of dumping one 3000-char tail.

    The FIRST failure aborts the remaining steps (printed as ``STEP
    <name> SKIP``): a failure inside a distributed op may leave this
    process's collective sequence out of lockstep with its peers, and
    running further collective steps against a desynced peer would hang
    or corrupt their verdicts. The test file reports skipped steps as
    inconclusive, naming the step that actually failed.
    """
    import traceback

    pid, nproc, port = (int(a) for a in sys.argv[1:4])
    ckpt_dir = sys.argv[4] if len(sys.argv) > 4 else None
    from tensorframes_tpu import parallel as par

    par.initialize(coordinator_address=f"localhost:{port}",
                   num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc

    mesh = par.cluster_mesh()
    assert mesh.num_data_shards == 4 * nproc

    # uneven local row counts: p0 gets 23 rows, p1 gets 17
    n_local = 23 if pid == 0 else 17
    base = 0 if pid == 0 else 1000
    k_local = (np.arange(n_local) % 5 + 10 * 0).astype(np.int64)
    x_local = (np.arange(n_local, dtype=np.float64) + base)
    v_local = np.stack([x_local, -x_local], 1)

    dist = par.distribute_local(
        {"k": k_local, "x": x_local, "v": v_local}, mesh)
    assert dist.num_rows == 40, dist.num_rows

    # global truth, identical on every process
    k_g = np.concatenate([(np.arange(23) % 5), (np.arange(17) % 5)])
    x_g = np.concatenate([np.arange(23.0), np.arange(17.0) + 1000])
    v_g = np.stack([x_g, -x_g], 1)

    def step_dmap():
        # dmap_blocks (row-local) + collect round trip
        out = par.dmap_blocks(lambda x: {"z": x * 2.0 + 1.0}, dist)
        rows = out.collect_frame().collect()
        got_z = np.sort(np.array([r["z"] for r in rows]))
        np.testing.assert_allclose(got_z, np.sort(x_g * 2 + 1), rtol=1e-12)

    def step_dreduce_monoid():
        # collective path with per-shard validity masks
        red = par.dreduce_blocks({"x": "sum", "v": "min"}, dist)
        np.testing.assert_allclose(red["x"], x_g.sum(), rtol=1e-12)
        np.testing.assert_allclose(red["v"], v_g.min(0), rtol=1e-12)

    def step_dreduce_generic():
        # arbitrary computation over ragged validity; reduce consumes
        # every column, so select the value column first
        red2 = par.dreduce_blocks(
            lambda x_input: {"x": jnp.sqrt((x_input ** 2).sum(0))},
            dist.select("x"))
        np.testing.assert_allclose(red2["x"], np.sqrt((x_g ** 2).sum()),
                                   rtol=1e-9)

    def step_daggregate_monoid():
        agg = par.daggregate({"x": "sum", "v": "max"}, dist, "k").collect()
        for r in agg:
            sel = k_g == r["k"]
            np.testing.assert_allclose(r["x"], x_g[sel].sum(), rtol=1e-12)
            np.testing.assert_allclose(r["v"], v_g[sel].max(0), rtol=1e-12)

    def step_daggregate_generic():
        # UDAF-analogue inside the "shuffle"; every value column must
        # back a fetch, so select key + value only
        agg2 = par.daggregate(
            lambda x_input: {"x": jnp.sqrt((x_input ** 2).sum(0))},
            dist.select(["k", "x"]), "k").collect()
        assert len(agg2) == 5
        for r in agg2:
            sel = k_g == r["k"]
            np.testing.assert_allclose(
                r["x"], np.sqrt((x_g[sel] ** 2).sum()), rtol=1e-9)

    def step_daggregate_device_keys():
        # DEVICE-side keys across processes (ids built by one jitted
        # sort-unique over the global sharded key column)
        agg3 = par.daggregate({"x": "sum"}, dist.select(["k", "x"]), "k",
                              max_groups=8).collect()
        assert len(agg3) == 5
        for r in agg3:
            sel = k_g == r["k"]
            np.testing.assert_allclose(r["x"], x_g[sel].sum(), rtol=1e-12)

    def step_dfilter():
        # per-shard compaction under the per-process pad layout, chained
        # into a collective reduce
        flt = par.dfilter(lambda x: x < 500.0, dist)  # only p0's rows
        assert flt.count() == 23, flt.count()
        fred = par.dreduce_blocks({"x": "sum"}, flt.select("x"))
        np.testing.assert_allclose(fred["x"], x_g[x_g < 500].sum(),
                                   rtol=1e-12)

    def step_dsort():
        # global order out of process-local shards, result normalized to
        # prefix validity (runs its own dfilter so steps stay independent)
        flt = par.dfilter(lambda x: x < 500.0, dist)
        srt = par.dsort("x", flt.select("x"), descending=True)
        assert srt.shard_valid is None
        top = srt.collect_frame().collect()
        np.testing.assert_allclose([r["x"] for r in top],
                                   np.sort(x_g[x_g < 500])[::-1],
                                   rtol=1e-12)

    def step_daggregate_composite_keys():
        # COMPOSITE device-side keys (mixed-radix int32 combination
        # inside one jitted program over the sharded key columns)
        k2_local = (np.arange(n_local) % 3).astype(np.int64)
        dist2 = par.distribute_local(
            {"k": k_local, "k2": k2_local, "x": x_local}, mesh)
        k2_g = np.concatenate([(np.arange(23) % 3), (np.arange(17) % 3)])
        agg4 = par.daggregate({"x": "sum"}, dist2, ["k", "k2"],
                              max_groups=16).collect()
        assert len(agg4) == len({(a, b) for a, b in zip(k_g, k2_g)})
        for r in agg4:
            sel = (k_g == r["k"]) & (k2_g == r["k2"])
            np.testing.assert_allclose(r["x"], x_g[sel].sum(), rtol=1e-12)

    def step_checkpoint_resume():
        # save + resume-on-mesh with BOTH processes participating: each
        # host writes/reads only its shards (orbax), restored arrays
        # carry the original shardings
        if not ckpt_dir:
            return
        from tensorframes_tpu.utils import checkpoint as ckpt

        state = {"x": dist.columns["x"], "v": dist.columns["v"]}
        ckpt.save(ckpt_dir, state)
        like = jax.tree.map(
            lambda a: jax.device_put(jnp.zeros(a.shape, a.dtype),
                                     a.sharding), state)
        restored = ckpt.restore(ckpt_dir, like=like)
        for name in state:
            a, b = state[name], restored[name]
            assert b.sharding == a.sharding, (name, b.sharding)
            for so, sn in zip(a.addressable_shards,
                              b.addressable_shards):
                np.testing.assert_array_equal(np.asarray(so.data),
                                              np.asarray(sn.data))

    steps = [
        ("dmap", step_dmap),
        ("dreduce_monoid", step_dreduce_monoid),
        ("dreduce_generic", step_dreduce_generic),
        ("daggregate_monoid", step_daggregate_monoid),
        ("daggregate_generic", step_daggregate_generic),
        ("daggregate_device_keys", step_daggregate_device_keys),
        ("dfilter", step_dfilter),
        ("dsort", step_dsort),
        ("daggregate_composite_keys", step_daggregate_composite_keys),
        ("checkpoint_resume", step_checkpoint_resume),
    ]
    assert [n for n, _ in steps] == STEP_NAMES  # one source of truth
    failed = False
    for i, (name, fn) in enumerate(steps):
        try:
            fn()
        except Exception:
            failed = True
            print(f"[worker {pid}] STEP {name} FAIL", flush=True)
            traceback.print_exc(file=sys.stdout)
            sys.stdout.flush()
            # a failure mid-collective leaves this process out of
            # lockstep; running more collective steps against a desynced
            # peer would hang — mark the rest skipped and stop
            for later, _ in steps[i + 1:]:
                print(f"[worker {pid}] STEP {later} SKIP", flush=True)
            break
        else:
            print(f"[worker {pid}] STEP {name} OK", flush=True)
    if not failed:
        print(f"[worker {pid}] OK", flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
