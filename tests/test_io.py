"""Columnar IO round trips: parquet, pandas, npz.

The reference's loader was Spark itself; the standalone framework reads
row groups straight into column blocks (no row-at-a-time convert path).
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import io as tio


def test_parquet_round_trip_scalar_vector_string(tmp_path):
    p = str(tmp_path / "t.parquet")
    n = 100
    rng = np.random.default_rng(0)
    df = tft.frame({
        "x": rng.standard_normal(n),
        "i": rng.integers(0, 50, n),
        "v": rng.standard_normal((n, 4)),
        "key": np.asarray([str(i % 7) for i in range(n)], object),
    }, num_partitions=3)
    tio.write_parquet(df, p)
    back = tio.read_parquet(p)
    assert back.count() == n
    a, b = df.collect(), back.collect()
    for ra, rb in zip(a, b):
        assert ra["key"] == rb["key"]
        assert ra["i"] == rb["i"]
        np.testing.assert_allclose(ra["x"], rb["x"])
        np.testing.assert_allclose(np.asarray(ra["v"]), np.asarray(rb["v"]))


def test_parquet_row_groups_become_partitions(tmp_path):
    p = str(tmp_path / "t.parquet")
    df = tft.frame({"x": np.arange(30.0)}, num_partitions=3)
    tio.write_parquet(df, p)
    back = tio.read_parquet(p)
    assert back.num_partitions == 3          # one per row group
    back2 = tio.read_parquet(p, num_partitions=5)
    assert back2.num_partitions == 5


def test_parquet_feeds_engine(tmp_path):
    p = str(tmp_path / "t.parquet")
    tio.write_parquet(tft.frame({"x": np.arange(10.0)}), p)
    df = tio.read_parquet(p)
    out = tft.map_blocks(lambda x: {"z": x + 3.0}, df)
    assert [r["z"] for r in out.collect()] == [i + 3.0 for i in range(10)]


def test_pandas_round_trip():
    import pandas as pd

    pdf = pd.DataFrame({"x": np.arange(5.0), "k": [str(i) for i in range(5)]})
    df = tio.from_pandas(pdf, num_partitions=2)
    assert df.count() == 5
    out = tio.to_pandas(tft.map_blocks(lambda x: {"z": x * 2}, df))
    assert list(out.columns) == ["x", "k", "z"]
    np.testing.assert_allclose(out["z"], np.arange(5.0) * 2)


def test_npz_round_trip(tmp_path):
    p = str(tmp_path / "t.npz")
    df = tft.frame({"x": np.arange(8.0), "v": np.arange(16.0).reshape(8, 2)})
    tio.write_npz(df, p)
    back = tio.read_npz(p, num_partitions=2)
    assert back.count() == 8
    np.testing.assert_allclose(
        [r["x"] for r in back.collect()], np.arange(8.0))


class TestCsv:
    def test_round_trip(self, tmp_path):
        p = str(tmp_path / "t.csv")
        df = tft.frame({"name": np.array(["a", "b", "c"], object),
                        "x": np.array([1.5, 2.5, 3.5]),
                        "n": np.array([1, 2, 3], np.int64)})
        tft.io.write_csv(df, p)
        back = tft.io.read_csv(p, num_partitions=2)
        rows = back.collect()
        assert [(r["name"], r["x"], r["n"]) for r in rows] == [
            ("a", 1.5, 1), ("b", 2.5, 2), ("c", 3.5, 3)]
        assert back.num_partitions == 2

    def test_columns_subset(self, tmp_path):
        p = str(tmp_path / "t.csv")
        tft.io.write_csv(tft.frame({"x": np.arange(3.0),
                                    "y": np.arange(3.0)}), p)
        back = tft.io.read_csv(p, columns=["y"])
        assert back.schema.names == ["y"]

    def test_vector_cells_rejected(self, tmp_path):
        df = tft.analyze(tft.frame({"v": np.ones((2, 3))}))
        with pytest.raises(ValueError, match="CSV cannot represent"):
            tft.io.write_csv(df, str(tmp_path / "t.csv"))

    def test_empty_columns_list_matches_parquet_semantics(self, tmp_path):
        p = str(tmp_path / "t.csv")
        tft.io.write_csv(tft.frame({"x": np.arange(3.0)}), p)
        assert tft.io.read_csv(p, columns=[]).schema.names == []


class TestRaggedParquet:
    """Variable-length list columns load as ragged columns (round-3 weak
    #7: they used to be rejected outright)."""

    def _write_ragged(self, tmp_path):
        df = tft.frame(
            [(np.arange(i + 1, dtype=np.float64), float(i))
             for i in range(6)],
            columns=["v", "x"], num_partitions=2)
        p = str(tmp_path / "ragged.parquet")
        tio.write_parquet(df, p)
        return p

    def test_round_trip_ragged(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p)
        rows = df.collect()
        assert len(rows) == 6
        for i, r in enumerate(rows):
            np.testing.assert_array_equal(r["v"], np.arange(i + 1))
            assert r["x"] == float(i)

    def test_ragged_feeds_map_rows(self, tmp_path):
        p = self._write_ragged(tmp_path)
        # analyze() stamps the ragged column's shape metadata (Unknown
        # inner dim) exactly as the reference required for variable rows
        df = tft.analyze(tio.read_parquet(p))
        out = tft.map_rows(lambda v: {"s": v.sum()}, df.select("v"))
        rows = out.collect()
        assert [r["s"] for r in rows] == [
            float(np.arange(i + 1).sum()) for i in range(6)]

    def test_pad_ragged_then_map_blocks(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p, pad_ragged=True)
        assert set(df.columns) >= {"v", "v_mask", "v_len"}
        out = tft.map_blocks(
            lambda v, v_mask: {"s": (v * v_mask).sum(axis=1)}, df)
        rows = out.collect()
        assert [r["s"] for r in rows] == [
            float(np.arange(i + 1).sum()) for i in range(6)]

    def test_pad_ragged_subset_list(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p, pad_ragged=["v"])
        assert "v_mask" in df.columns

    def test_repartition_keeps_ragged(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p, num_partitions=3)
        assert df.num_partitions == 3
        rows = df.collect()
        np.testing.assert_array_equal(rows[4]["v"], np.arange(5))
