"""Columnar IO round trips: parquet, pandas, npz.

The reference's loader was Spark itself; the standalone framework reads
row groups straight into column blocks (no row-at-a-time convert path).
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import io as tio


def test_parquet_round_trip_scalar_vector_string(tmp_path):
    p = str(tmp_path / "t.parquet")
    n = 100
    rng = np.random.default_rng(0)
    df = tft.frame({
        "x": rng.standard_normal(n),
        "i": rng.integers(0, 50, n),
        "v": rng.standard_normal((n, 4)),
        "key": np.asarray([str(i % 7) for i in range(n)], object),
    }, num_partitions=3)
    tio.write_parquet(df, p)
    back = tio.read_parquet(p)
    assert back.count() == n
    a, b = df.collect(), back.collect()
    for ra, rb in zip(a, b):
        assert ra["key"] == rb["key"]
        assert ra["i"] == rb["i"]
        np.testing.assert_allclose(ra["x"], rb["x"])
        np.testing.assert_allclose(np.asarray(ra["v"]), np.asarray(rb["v"]))


class TestParquetColumnProjection:
    """``read_parquet(columns=)`` selects columns at READ time
    (footer-driven): unrequested columns are never materialized, and
    the projection composes with ``row_group_offset``/``row_group_limit``
    (the logical plan's pruning pushes through this — docs/plan.md)."""

    @pytest.fixture
    def wide_file(self, tmp_path):
        p = str(tmp_path / "wide.parquet")
        n = 60
        cols = {"a": np.arange(float(n)),
                "b": np.arange(n).astype(np.int64),
                "c": np.ones((n, 2)),
                "d": np.asarray([f"s{i}" for i in range(n)], object)}
        tio.write_parquet(tft.frame(cols, num_partitions=3), p)
        return p, cols, n

    def test_projection_reads_only_requested(self, wide_file, monkeypatch):
        p, cols, n = wide_file
        decoded = []
        real = tio._column_to_numpy
        monkeypatch.setattr(tio, "_column_to_numpy",
                            lambda col, name: decoded.append(name)
                            or real(col, name))
        back = tio.read_parquet(p, columns=["a", "d"])
        assert back.schema.names == ["a", "d"]
        assert back.count() == n
        # unread columns were never materialized: the decoder only ever
        # saw the requested names
        assert set(decoded) == {"a", "d"}
        got = np.concatenate([blk.columns["a"] for blk in back.blocks()])
        assert np.array_equal(got, cols["a"])

    def test_projection_composes_with_row_groups(self, wide_file):
        p, cols, n = wide_file
        part = tio.read_parquet(p, columns=["b"], row_group_offset=1,
                                row_group_limit=1)
        got = np.concatenate([blk.columns["b"] for blk in part.blocks()])
        # 60 rows over 3 row groups: group 1 holds rows 20..39
        assert np.array_equal(got, cols["b"][20:40])
        assert part.schema.names == ["b"]

    def test_unknown_column_rejected(self, wide_file):
        p, _, _ = wide_file
        with pytest.raises(ValueError, match="nope"):
            tio.read_parquet(p, columns=["a", "nope"])

    def test_lazy_schema_matches_eager_decode(self, wide_file):
        p, _, _ = wide_file
        lazy = tio.read_parquet(p)
        pre = lazy.schema  # footer-derived, nothing read yet
        assert lazy._cache is None
        eager = tio._read_parquet_eager(p, None, None, False, 0, None)
        assert pre == eager.schema
        assert lazy.num_partitions == eager.num_partitions

    def test_nullable_int_column_falls_back_to_eager(self, tmp_path):
        # int-with-nulls decodes as float64 NaN (pyarrow to_numpy); a
        # footer-typed int64 schema would silently disagree with the
        # data — such files must keep the eager data-derived schema
        import pyarrow as pa
        import pyarrow.parquet as pq
        p = str(tmp_path / "nulls.parquet")
        pq.write_table(pa.table({"i": pa.array([1, None, 3], pa.int64()),
                                 "f": pa.array([1.0, 2.0, 3.0])}), p)
        back = tio.read_parquet(p)
        assert back._plan_node is None  # eager, like before the plan
        blk = back.blocks()[0]
        assert blk.columns["i"].dtype == np.float64
        assert back.schema["i"].dtype.name == "double"
        assert np.isnan(blk.columns["i"][1])

    def test_float_nulls_stay_lazy_and_decode_nan(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq
        p = str(tmp_path / "fnull.parquet")
        pq.write_table(pa.table({"f": pa.array([1.0, None, 3.0])}), p)
        back = tio.read_parquet(p)
        assert back._plan_node is not None  # NaN round-trips losslessly
        assert np.isnan(back.blocks()[0].columns["f"][1])

    def test_lazy_rows_bytes_hints_from_footer(self, wide_file):
        p, cols, n = wide_file
        lazy = tio.read_parquet(p, columns=["a"])
        assert lazy._cache is None
        assert lazy.estimated_rows() == n
        assert lazy.estimated_bytes() > 0


def test_parquet_row_groups_become_partitions(tmp_path):
    p = str(tmp_path / "t.parquet")
    df = tft.frame({"x": np.arange(30.0)}, num_partitions=3)
    tio.write_parquet(df, p)
    back = tio.read_parquet(p)
    assert back.num_partitions == 3          # one per row group
    back2 = tio.read_parquet(p, num_partitions=5)
    assert back2.num_partitions == 5


def test_parquet_feeds_engine(tmp_path):
    p = str(tmp_path / "t.parquet")
    tio.write_parquet(tft.frame({"x": np.arange(10.0)}), p)
    df = tio.read_parquet(p)
    out = tft.map_blocks(lambda x: {"z": x + 3.0}, df)
    assert [r["z"] for r in out.collect()] == [i + 3.0 for i in range(10)]


def test_pandas_round_trip():
    import pandas as pd

    pdf = pd.DataFrame({"x": np.arange(5.0), "k": [str(i) for i in range(5)]})
    df = tio.from_pandas(pdf, num_partitions=2)
    assert df.count() == 5
    out = tio.to_pandas(tft.map_blocks(lambda x: {"z": x * 2}, df))
    assert list(out.columns) == ["x", "k", "z"]
    np.testing.assert_allclose(out["z"], np.arange(5.0) * 2)


def test_npz_round_trip(tmp_path):
    p = str(tmp_path / "t.npz")
    df = tft.frame({"x": np.arange(8.0), "v": np.arange(16.0).reshape(8, 2)})
    tio.write_npz(df, p)
    back = tio.read_npz(p, num_partitions=2)
    assert back.count() == 8
    np.testing.assert_allclose(
        [r["x"] for r in back.collect()], np.arange(8.0))


class TestCsv:
    def test_round_trip(self, tmp_path):
        p = str(tmp_path / "t.csv")
        df = tft.frame({"name": np.array(["a", "b", "c"], object),
                        "x": np.array([1.5, 2.5, 3.5]),
                        "n": np.array([1, 2, 3], np.int64)})
        tft.io.write_csv(df, p)
        back = tft.io.read_csv(p, num_partitions=2)
        rows = back.collect()
        assert [(r["name"], r["x"], r["n"]) for r in rows] == [
            ("a", 1.5, 1), ("b", 2.5, 2), ("c", 3.5, 3)]
        assert back.num_partitions == 2

    def test_columns_subset(self, tmp_path):
        p = str(tmp_path / "t.csv")
        tft.io.write_csv(tft.frame({"x": np.arange(3.0),
                                    "y": np.arange(3.0)}), p)
        back = tft.io.read_csv(p, columns=["y"])
        assert back.schema.names == ["y"]

    def test_vector_cells_rejected(self, tmp_path):
        df = tft.analyze(tft.frame({"v": np.ones((2, 3))}))
        with pytest.raises(ValueError, match="CSV cannot represent"):
            tft.io.write_csv(df, str(tmp_path / "t.csv"))

    def test_empty_columns_list_matches_parquet_semantics(self, tmp_path):
        p = str(tmp_path / "t.csv")
        tft.io.write_csv(tft.frame({"x": np.arange(3.0)}), p)
        assert tft.io.read_csv(p, columns=[]).schema.names == []


class TestRaggedParquet:
    """Variable-length list columns load as ragged columns (round-3 weak
    #7: they used to be rejected outright)."""

    def _write_ragged(self, tmp_path):
        df = tft.frame(
            [(np.arange(i + 1, dtype=np.float64), float(i))
             for i in range(6)],
            columns=["v", "x"], num_partitions=2)
        p = str(tmp_path / "ragged.parquet")
        tio.write_parquet(df, p)
        return p

    def test_round_trip_ragged(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p)
        rows = df.collect()
        assert len(rows) == 6
        for i, r in enumerate(rows):
            np.testing.assert_array_equal(r["v"], np.arange(i + 1))
            assert r["x"] == float(i)

    def test_ragged_feeds_map_rows(self, tmp_path):
        p = self._write_ragged(tmp_path)
        # analyze() stamps the ragged column's shape metadata (Unknown
        # inner dim) exactly as the reference required for variable rows
        df = tft.analyze(tio.read_parquet(p))
        out = tft.map_rows(lambda v: {"s": v.sum()}, df.select("v"))
        rows = out.collect()
        assert [r["s"] for r in rows] == [
            float(np.arange(i + 1).sum()) for i in range(6)]

    def test_pad_ragged_then_map_blocks(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p, pad_ragged=True)
        assert set(df.columns) >= {"v", "v_mask", "v_len"}
        out = tft.map_blocks(
            lambda v, v_mask: {"s": (v * v_mask).sum(axis=1)}, df)
        rows = out.collect()
        assert [r["s"] for r in rows] == [
            float(np.arange(i + 1).sum()) for i in range(6)]

    def test_pad_ragged_subset_list(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p, pad_ragged=["v"])
        assert "v_mask" in df.columns

    def test_repartition_keeps_ragged(self, tmp_path):
        p = self._write_ragged(tmp_path)
        df = tio.read_parquet(p, num_partitions=3)
        assert df.num_partitions == 3
        rows = df.collect()
        np.testing.assert_array_equal(rows[4]["v"], np.arange(5))

    def test_fused_pad_matches_pad_column(self, tmp_path):
        # read_parquet(pad_ragged=True) pads straight from the arrow
        # offsets+values buffers (no per-cell work); it must be
        # indistinguishable from loading ragged cells then pad_column
        p = self._write_ragged(tmp_path)
        fused = tio.read_parquet(p, pad_ragged=True)
        stepwise = tio.read_parquet(p).pad_column("v")
        assert fused.schema.names == stepwise.schema.names
        for f_f, f_s in zip(fused.schema, stepwise.schema):
            assert (f_f.name, f_f.dtype, f_f.sql_rank) == \
                (f_s.name, f_s.dtype, f_s.sql_rank)
            assert (f_f.block_shape is None) == (f_s.block_shape is None)
            if f_f.block_shape is not None:
                assert f_f.block_shape.dims == f_s.block_shape.dims
        fr, sr = fused.collect(), stepwise.collect()
        assert len(fr) == len(sr)
        for a, b in zip(fr, sr):
            for c in fused.schema.names:
                np.testing.assert_array_equal(a[c], b[c])

    def test_fused_pad_empty_and_uniform_cells(self, tmp_path):
        # empty cells pad to all-mask-zero rows; a row GROUP whose cells
        # happen to share one length decodes dense and must still fold
        # into the global pad width
        import pyarrow as pa
        import pyarrow.parquet as pq

        p = str(tmp_path / "mixed.parquet")
        writer = None
        try:
            # row group 1: ragged incl. an empty cell
            t1 = pa.table({"v": pa.array(
                [[1.0, 2.0, 3.0], [], [4.0]])})
            # row group 2: uniform length 2 (decodes dense)
            t2 = pa.table({"v": pa.array([[5.0, 6.0], [7.0, 8.0]])})
            writer = pq.ParquetWriter(p, t1.schema)
            writer.write_table(t1)
            writer.write_table(t2)
        finally:
            if writer is not None:
                writer.close()
        df = tio.read_parquet(p, pad_ragged=True)
        rows = df.collect()
        assert [r["v_len"] for r in rows] == [3, 0, 1, 2, 2]
        np.testing.assert_array_equal(rows[0]["v"], [1.0, 2.0, 3.0])
        np.testing.assert_array_equal(rows[1]["v_mask"], [0, 0, 0])
        np.testing.assert_array_equal(rows[3]["v"], [5.0, 6.0, 0.0])
        # parity with the stepwise path on the same file
        stepwise = tio.read_parquet(p).pad_column("v")
        for a, b in zip(rows, stepwise.collect()):
            for c in df.schema.names:
                np.testing.assert_array_equal(a[c], b[c])
