"""Streaming execution suite (tier-1; marker ``stream``).

Proves the streaming subsystem's contract end-to-end on CPU:

- block sources: schema inference + checking, bounded-queue
  backpressure, parquet tailing that re-reads nothing
  (``io.read_parquet(row_group_offset=)``);
- **finite equivalence**: streaming a finite parquet through every
  supported relational op matches the batch ``TensorFrame`` path
  bit-identically, ordering included;
- windows & watermarks: tumbling/sliding emission timing, exact
  contents, late-batch drop-and-count, finalize flush, update mode;
- the ≥100-batch keyed-aggregation demo: device-resident state stays
  bounded (rows/bytes plateau) and per-batch work is cache-hit after
  warmup (no engine compile-cache misses, no merge-program builds past
  the first batches);
- per-batch failure isolation via the ``batch`` fault site: transient
  faults retry, poisoned batches skip-and-count, the stream survives;
- sinks (collect/callback/parquet appender) and the ``tft_stream_*``
  metrics; slot-pool sharing with the serving layer's global bound.
"""

import queue as queue_mod
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import io as tio
from tensorframes_tpu import stream
from tensorframes_tpu.engine import pipeline as engine_pipeline
from tensorframes_tpu.observability import metrics as obs_metrics
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.schema import Schema
from tensorframes_tpu.utils import tracing

pytestmark = pytest.mark.stream


@pytest.fixture(autouse=True)
def _fast_and_clean(monkeypatch):
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("TFT_RETRY_MAX_DELAY", "0.01")
    tracing.disable()
    tracing.counters.reset()
    faults.reset()
    yield
    faults.reset()


def _batches(n, rows=4, keys=2, t0=0.0, dt=1.0):
    """n batches of `rows` rows: int64 key cycling [0, keys), double
    value, double event time (one timestamp per batch)."""
    for i in range(n):
        yield {"k": (np.arange(rows) % keys).astype(np.int64),
               "v": np.arange(rows, dtype=np.float64) + i,
               "ts": np.full(rows, t0 + i * dt)}


def _rows(frames):
    return [r for f in frames for r in f.collect()]


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

class TestSources:
    def test_generator_infers_schema_and_ends(self):
        src = stream.GeneratorSource(_batches(2))
        assert src.schema.names == ["k", "v", "ts"]
        assert src.poll() is not None and src.poll() is not None
        assert src.poll() is None
        assert src.done()

    def test_schema_drift_is_named(self):
        def gen():
            yield {"x": np.arange(3.0)}
            yield {"y": np.arange(3.0)}          # renamed column

        src = stream.GeneratorSource(gen())
        assert src.poll() is not None
        with pytest.raises(stream.SchemaMismatch, match="missing"):
            src.poll()

    def test_dtype_drift_is_named(self):
        def gen():
            yield {"x": np.arange(3.0)}
            yield {"x": np.arange(3, dtype=np.float32)}

        src = stream.GeneratorSource(gen())
        assert src.poll() is not None
        with pytest.raises(stream.SchemaMismatch, match="float32"):
            src.poll()

    def test_queue_backpressure_and_close(self):
        src = stream.QueueSource(Schema.of(x="double"), maxsize=1)
        src.put({"x": np.arange(2.0)})
        with pytest.raises(queue_mod.Full):     # the bound pushes back
            src.put({"x": np.arange(2.0)}, timeout=0.01)
        src.close()
        with pytest.raises(RuntimeError):
            src.put({"x": np.arange(2.0)})
        assert not src.done()                   # still one block queued
        assert src.poll() is not None
        assert src.done()

    def test_queue_checks_at_producer(self):
        src = stream.QueueSource(Schema.of(x="double"), maxsize=4)
        with pytest.raises(stream.SchemaMismatch):
            src.put({"x": np.arange(3, dtype=np.int32)})

    def test_parquet_tail_reads_only_new_row_groups(self, tmp_path):
        path = str(tmp_path / "t.parquet")
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        tio.write_parquet(df, path)
        src = stream.ParquetTailSource(path)
        got = [src.poll(), src.poll()]
        assert [b.num_rows for b in got] == [4, 4]
        assert src.poll() is None and not src.done()  # follow mode
        # the writer replaces the file with a longer one (the parquet
        # append idiom); only the NEW groups come back
        df2 = tft.frame({"x": np.arange(16.0)}, num_partitions=4)
        tio.write_parquet(df2, path)
        b3 = src.poll()
        np.testing.assert_array_equal(b3.columns["x"], np.arange(8.0, 12.0))
        assert src.poll().num_rows == 4 and src.poll() is None

    def test_read_parquet_row_group_offset(self, tmp_path):
        path = str(tmp_path / "o.parquet")
        tio.write_parquet(
            tft.frame({"x": np.arange(9.0),
                       "s": np.array(["a"] * 9, object)},
                      num_partitions=3), path)
        part = tio.read_parquet(path, row_group_offset=1)
        assert part.num_partitions == 2
        np.testing.assert_array_equal(
            np.concatenate([b.columns["x"] for b in part.blocks()]),
            np.arange(3.0, 9.0))
        # past-the-end: empty but TYPED from the parquet footer
        empty = tio.read_parquet(path, row_group_offset=17)
        assert empty.count() == 0
        assert empty.schema["x"].dtype.name == "double"
        assert empty.schema["s"].dtype.name == "string"
        with pytest.raises(ValueError, match="row_group_offset"):
            tio.read_parquet(path, row_group_offset=-1)


# ---------------------------------------------------------------------------
# finite-source equivalence (acceptance: bit-identical, ordering included)
# ---------------------------------------------------------------------------

class TestFiniteEquivalence:
    @pytest.fixture
    def pq_file(self, tmp_path):
        path = str(tmp_path / "f.parquet")
        rng = np.random.default_rng(7)
        df = tft.frame(
            {"x": rng.normal(size=20),
             "k": (np.arange(20) % 4).astype(np.int64)},
            num_partitions=5)
        tio.write_parquet(df, path)
        return path

    def _stream_rows(self, sf):
        h = sf.start()
        h.run()
        frames = h.collect_updates()
        return _rows(frames)

    @staticmethod
    def _assert_identical(got, want):
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g.fields == w.fields
            for a, b in zip(g, w):
                assert np.asarray(a).dtype == np.asarray(b).dtype
                np.testing.assert_array_equal(a, b)

    def test_map_blocks(self, pq_file):
        fetch = lambda x: {"y": x * 2.0 + 1.0}  # noqa: E731
        got = self._stream_rows(
            stream.from_source(
                stream.ParquetTailSource(pq_file, follow=False))
            .map_blocks(fetch))
        want = tio.read_parquet(pq_file).map_blocks(fetch).collect()
        self._assert_identical(got, want)

    def test_map_blocks_trim(self, pq_file):
        fetch = lambda x: {"y": x - 3.0}  # noqa: E731
        got = self._stream_rows(
            stream.from_source(
                stream.ParquetTailSource(pq_file, follow=False))
            .map_blocks(fetch, trim=True))
        want = tio.read_parquet(pq_file).map_blocks(
            fetch, trim=True).collect()
        self._assert_identical(got, want)

    def test_map_rows(self, pq_file):
        fetch = lambda x: {"y": x * x}  # noqa: E731
        got = self._stream_rows(
            stream.from_source(
                stream.ParquetTailSource(pq_file, follow=False))
            .map_rows(fetch))
        want = tft.map_rows(fetch,
                            tio.read_parquet(pq_file)).collect()
        self._assert_identical(got, want)

    def test_filter_and_select(self, pq_file):
        pred = lambda k: k != 2  # noqa: E731
        got = self._stream_rows(
            stream.from_source(
                stream.ParquetTailSource(pq_file, follow=False))
            .filter_rows(pred).select(["x"]))
        want = tio.read_parquet(pq_file).filter(pred) \
            .select(["x"]).collect()
        self._assert_identical(got, want)

    def test_chained_ops(self, pq_file):
        def chain_stream(sf):
            return (sf.map_blocks(lambda x: {"y": x + 1.0})
                    .filter_rows(lambda k: k != 0)
                    .map_rows(lambda y: {"z": y * y})
                    .select(["k", "z"]))

        got = self._stream_rows(chain_stream(
            stream.from_source(
                stream.ParquetTailSource(pq_file, follow=False))))
        df = tio.read_parquet(pq_file)
        df = df.map_blocks(lambda x: {"y": x + 1.0})
        df = df.filter(lambda k: k != 0)
        df = tft.map_rows(lambda y: {"z": y * y}, df)
        want = df.select(["k", "z"]).collect()
        self._assert_identical(got, want)

    def test_definition_time_validation(self):
        sf = stream.from_source(
            stream.GeneratorSource(_batches(1)))
        with pytest.raises(Exception, match="no matching column"):
            sf.map_blocks(lambda nope: {"y": nope})
        with pytest.raises(KeyError):
            sf.select(["missing"])


# ---------------------------------------------------------------------------
# windows, watermarks, late data
# ---------------------------------------------------------------------------

class TestWindowsAndWatermarks:
    def _agg(self, gen, window, delay=0.0, **kw):
        return (stream.from_source(stream.GeneratorSource(gen))
                .group_by("k")
                .aggregate({"v": "sum"}, window=window, time_col="ts",
                           watermark_delay=delay, **kw))

    def test_tumbling_emits_exactly_at_watermark(self):
        h = self._agg(_batches(12), stream.tumbling(4.0), delay=1.0) \
            .start()
        emitted_at = {}
        n = 0
        while not h.done():
            if h.step():
                n += 1
            # drain after EVERY step: the finalize flush arrives on the
            # exhausting step, which returns False
            for f in h.collect_updates():
                s = f.collect()[0]["window_start"]
                emitted_at[float(s)] = n
        # watermark = max_ts - 1; window [0,4) closes when wm >= 4,
        # i.e. after the batch at ts=5 (the 6th batch)
        assert emitted_at[0.0] == 6
        assert emitted_at[4.0] == 10
        assert emitted_at[8.0] == 12  # flushed by finalize

    def test_window_contents_match_batch_aggregate(self):
        h = self._agg(_batches(12), stream.tumbling(4.0), delay=1.0) \
            .start()
        h.run()
        frames = h.collect_updates()
        by_window = {float(f.collect()[0]["window_start"]): f
                     for f in frames}
        # reference: the finite monoid aggregate over the same rows
        all_rows = {"k": [], "v": [], "ts": []}
        for b in _batches(12):
            for c in all_rows:
                all_rows[c].append(b[c])
        full = tft.frame({c: np.concatenate(v)
                          for c, v in all_rows.items()})
        for start in (0.0, 4.0, 8.0):
            wdf = full.filter(
                lambda ts: (ts >= start) & (ts < start + 4.0))
            want = tft.aggregate({"v": "sum"},
                                 wdf.select(["k", "v"]).group_by("k"))
            got = by_window[start]
            np.testing.assert_array_equal(
                got.blocks()[0].columns["k"],
                want.blocks()[0].columns["k"])
            np.testing.assert_allclose(
                got.blocks()[0].columns["v"],
                want.blocks()[0].columns["v"])

    def test_late_batch_is_dropped_and_counted(self):
        def gen():
            yield from _batches(8)               # ts 0..7
            # a straggler for the long-closed first window
            yield {"k": np.array([0], np.int64),
                   "v": np.array([100.0]), "ts": np.array([0.5])}

        h = self._agg(gen(), stream.tumbling(2.0), delay=1.0).start()
        h.run()
        frames = h.collect_updates()
        # the late 100.0 must not appear in ANY window
        assert all(r["v"] < 100.0 for r in _rows(frames))
        assert h.metrics()["late_rows"] == 1
        assert tracing.counters.get("stream.late_rows") == 1

    def test_sliding_rows_land_in_every_overlapping_window(self):
        def gen():
            yield {"k": np.array([0], np.int64),
                   "v": np.array([1.0]), "ts": np.array([5.0])}
            yield {"k": np.array([0], np.int64),
                   "v": np.array([0.0]), "ts": np.array([30.0])}

        h = self._agg(gen(), stream.sliding(4.0, 2.0)).start()
        h.run()
        out = {float(r["window_start"]): r["v"]
               for r in _rows(h.collect_updates())}
        # ts=5 belongs to [4,8) and [2,6); ts=30 to [28,32) and [30,34)
        assert out[4.0] == 1.0 and out[2.0] == 1.0
        assert 0.0 not in out or out[0.0] == 0.0

    def test_update_mode_running_totals(self):
        src = stream.GeneratorSource(_batches(3, rows=2, keys=2))
        h = (stream.from_source(src).group_by("k")
             .aggregate({"v": "sum"}).start())
        h.run()
        frames = h.collect_updates()
        # per-batch deltas plus the finalize snapshot; the last frame is
        # the full running total: k=0 gets v[0]=i, k=1 gets v[1]=i+1
        final = {r["k"]: r["v"] for r in frames[-1].collect()}
        assert final == {0: 0.0 + 1 + 2, 1: 1.0 + 2 + 3}

    def test_windowed_needs_time_col_and_update_rejects_cap(self):
        g = stream.from_source(
            stream.GeneratorSource(_batches(1))).group_by("k")
        with pytest.raises(ValueError, match="time_col"):
            g.aggregate({"v": "sum"}, window=stream.tumbling(4.0))
        with pytest.raises(ValueError, match="max_state_rows"):
            g.aggregate({"v": "sum"}, max_state_rows=10)
        with pytest.raises(ValueError, match="Unknown combiner"):
            g.aggregate({"v": "median"}, window=stream.tumbling(4.0),
                        time_col="ts")


# ---------------------------------------------------------------------------
# bounded state + cache-hit steady state (the >=100-batch acceptance demo)
# ---------------------------------------------------------------------------

class TestBoundedStateDemo:
    def test_100_plus_batches_bounded_state_and_no_recompiles(self):
        n_batches, keys = 120, 8
        agg = (stream.from_source(
                   stream.GeneratorSource(
                       _batches(n_batches, rows=16, keys=keys)))
               .map_blocks(lambda v: {"v2": v * 2.0})
               .select(["k", "v2", "ts"])
               .group_by("k")
               .aggregate({"v2": "sum"}, window=stream.tumbling(8.0),
                          time_col="ts", watermark_delay=4.0))
        h = agg.start(name="demo")
        peak_rows = peak_bytes = 0
        warmup_mark = None
        processed = 0
        while not h.done():
            if not h.step():
                continue
            processed += 1
            m = h.metrics()
            peak_rows = max(peak_rows, m["state_rows"])
            peak_bytes = max(peak_bytes, m["state_bytes"])
            if processed == 20:  # steady state reached
                warmup_mark = (
                    tracing.counters.get("compile_cache.misses"),
                    tracing.counters.get("stream.merge_compiles"))
        assert processed == n_batches
        assert h.metrics()["batches_skipped"] == 0
        # bounded device-resident state: watermark delay 4 keeps at most
        # ceil((8+4)/8)+1 = 3 windows open, `keys` rows each — the
        # plateau the acceptance criterion asks for
        assert 0 < peak_rows <= 3 * keys
        assert peak_bytes > 0
        # steady state is pure cache hits: no engine compile-cache
        # misses and no merge-program builds after warmup
        assert (tracing.counters.get("compile_cache.misses"),
                tracing.counters.get("stream.merge_compiles")) \
            == warmup_mark
        # and the emitted totals are complete: every batch contributes
        # sum(2*(i + [0..15])) to its window; check the grand total
        frames = h.collect_updates()
        got_total = sum(float(np.sum(f.blocks()[0].columns["v2"]))
                        for f in frames)
        want_total = sum(2.0 * (16 * i + np.arange(16.0).sum())
                         for i in range(n_batches))
        assert got_total == pytest.approx(want_total)
        assert h.metrics()["windows_emitted"] == n_batches / 8

    def test_max_state_rows_force_evicts_oldest(self):
        # watermark never advances enough to emit (huge delay): the cap
        # is the only thing bounding state
        agg = (stream.from_source(
                   stream.GeneratorSource(
                       _batches(30, rows=8, keys=4)))
               .group_by("k")
               .aggregate({"v": "sum"}, window=stream.tumbling(2.0),
                          time_col="ts", watermark_delay=1000.0,
                          max_state_rows=12))
        h = agg.start()
        while not h.done():
            if h.step():
                assert h.metrics()["state_rows"] <= 12
        assert h.metrics()["state_evictions"] > 0
        assert tracing.counters.get("stream.state_evictions") > 0


class TestWindowStateInGlobalLRU:
    """PR 8 follow-on: window state is a registered entry in the global
    memory LRU — the LEDGER drives its spills under pressure, not just
    the stream's own ``max_state_rows`` cap (``docs/memory.md``)."""

    def test_ledger_pressure_spills_window_state(self):
        from tensorframes_tpu import memory
        memory.configure(limit_bytes=1 << 20)
        try:
            agg = (stream.from_source(
                       stream.GeneratorSource(
                           _batches(6, rows=64, keys=32)))
                   .group_by("k")
                   .aggregate({"v": "sum"}, window=stream.tumbling(2.0),
                              time_col="ts", watermark_delay=1000.0))
            h = agg.start()
            assert h.step()  # one committed, ledger-registered window
            spills0 = tracing.counters.get("stream.state_spills")
            # admission squeeze from ANYWHERE in the process: a reserve
            # close to the whole budget must push the coldest resident
            # (the window state) to host through the LRU
            mgr = memory.active()
            tok = mgr.reserve((1 << 20) - 64, op="test.pressure")
            mgr.release(tok)
            assert tracing.counters.get("stream.state_spills") > spills0
            assert agg.state_spills > 0
            # the window stayed LIVE: the rest of the stream folds into
            # it (transparent fault-back) and totals stay exact
            h.run()
            frames = h.collect_updates()
            got = sum(float(np.sum(f.blocks()[0].columns["v"]))
                      for f in frames)
            want = sum(float(np.sum(b["v"]))
                       for b in _batches(6, rows=64, keys=32))
            assert got == pytest.approx(want)
        finally:
            memory._reset()

    def test_no_ledger_registration_when_unlimited(self):
        from tensorframes_tpu import memory
        memory.configure(limit_bytes=0)
        try:
            agg = (stream.from_source(
                       stream.GeneratorSource(_batches(2)))
                   .group_by("k")
                   .aggregate({"v": "sum"}, window=stream.tumbling(2.0),
                              time_col="ts", watermark_delay=1000.0))
            h = agg.start()
            h.run()
            assert agg.state_spills == 0
        finally:
            memory._reset()


# ---------------------------------------------------------------------------
# per-batch failure isolation (acceptance: `batch` fault site)
# ---------------------------------------------------------------------------

class TestFailureIsolation:
    def test_poisoned_batch_skipped_stream_survives(self):
        sf = stream.from_source(
            stream.GeneratorSource(_batches(5))) \
            .map_blocks(lambda v: {"y": v + 1.0})
        h = sf.start(name="poison")
        # arm AFTER batch 0: deterministic — exactly batch 1 poisons
        assert h.step()
        with faults.inject("batch", fail_n=1, transient=False):
            h.run()
        m = h.metrics()
        assert m["batches_skipped"] == 1
        assert m["batches"] == 4
        assert tracing.counters.get("stream.batches_skipped") == 1
        assert h.done()
        # exactly the poisoned batch's rows are missing
        got = _rows(h.collect_updates())
        assert len(got) == 4 * 4
        batches_seen = sorted({float(r["ts"]) for r in got})
        assert batches_seen == [0.0, 2.0, 3.0, 4.0]

    def test_transient_batch_fault_retries_not_skips(self):
        sf = stream.from_source(stream.GeneratorSource(_batches(3)))
        h = sf.start(name="flaky")
        with faults.inject("batch", fail_n=1):   # transient (default)
            h.run()
        assert h.metrics()["batches_skipped"] == 0
        assert h.metrics()["batches"] == 3
        assert tracing.counters.get("retry.stream.batch.retries") == 1

    def test_transient_fault_never_double_counts_aggregation(self):
        # the retry policy wraps only the forcing — ingest commits once
        # — so a retried batch must not fold twice into window state
        agg = (stream.from_source(
                   stream.GeneratorSource(_batches(8, rows=4, keys=2)))
               .group_by("k")
               .aggregate({"v": "sum"}, window=stream.tumbling(4.0),
                          time_col="ts", watermark_delay=0.0))
        h = agg.start()
        assert h.step()                       # batch 0 clean
        with faults.inject("batch", fail_n=1):  # transient: retried
            h.run()
        assert h.metrics()["batches_skipped"] == 0
        frames = h.collect_updates()
        total = sum(float(np.sum(f.blocks()[0].columns["v"]))
                    for f in frames)
        want = sum(4 * i + np.arange(4.0).sum() for i in range(8))
        assert total == pytest.approx(want)

    def test_failed_ingest_leaves_state_untouched(self, monkeypatch):
        # ingest is all-or-nothing: poison the MERGE step of batch 2 and
        # the whole batch must skip with window state exactly as it was
        from tensorframes_tpu.stream import aggregate as agg_mod

        a = (stream.from_source(
                 stream.GeneratorSource(_batches(3, rows=4, keys=2)))
             .group_by("k")
             .aggregate({"v": "sum"}, window=stream.tumbling(100.0),
                        time_col="ts"))
        h = a.start()
        assert h.step()
        before = (a.state_rows, {k: dict(w.values)
                                 for k, w in a._windows.items()})

        real = agg_mod._merge_program

        def poisoned(*args, **kw):
            raise ValueError("deterministic merge poison")

        monkeypatch.setattr(agg_mod, "_merge_program", poisoned)
        assert h.step()                       # consumed, but skipped
        assert h.metrics()["batches_skipped"] == 1
        assert a.state_rows == before[0]
        monkeypatch.setattr(agg_mod, "_merge_program", real)
        assert h.step()                       # stream continues cleanly
        assert h.metrics()["batches"] == 2

    def test_corrupt_tail_row_group_cannot_livelock(self, tmp_path,
                                                    monkeypatch):
        path = str(tmp_path / "c.parquet")
        tio.write_parquet(
            tft.frame({"x": np.arange(8.0)}, num_partitions=2), path)
        src = stream.ParquetTailSource(path, skip_unreadable_after_s=0.0)
        # the source reads through the EAGER entry (one footer read per
        # poll; lazy frames would defer decode errors) — patch that
        real = tio._read_parquet_eager

        def corrupt(p, *a, **kw):
            raise ValueError("corrupt row group data")

        monkeypatch.setattr(tio, "_read_parquet_eager", corrupt)
        # three consecutive failures at the same offset (past the
        # wall-clock floor, zeroed for the test), then the source steps
        # past the unreadable group — forward progress, not a spin
        for _ in range(3):
            with pytest.raises(ValueError):
                src.poll()
        monkeypatch.setattr(tio, "_read_parquet_eager", real)
        b = src.poll()                        # group 0 was skipped
        np.testing.assert_array_equal(b.columns["x"],
                                      np.arange(4.0, 8.0))

    def test_corrupt_group_does_not_discard_readable_neighbors(
            self, tmp_path, monkeypatch):
        # groups 0..2; group 1 is "corrupt". The degraded single-group
        # reads must deliver groups 0 and 2 and skip ONLY group 1.
        path = str(tmp_path / "mid.parquet")
        tio.write_parquet(
            tft.frame({"x": np.arange(12.0)}, num_partitions=3), path)
        src = stream.ParquetTailSource(path, skip_unreadable_after_s=0.0)
        real = tio._read_parquet_eager

        def selective(p, *a, row_group_offset=0, row_group_limit=None,
                      **kw):
            end = (row_group_offset + row_group_limit
                   if row_group_limit is not None else 3)
            if row_group_offset <= 1 < end:
                raise ValueError("corrupt row group 1")
            return real(p, *a, row_group_offset=row_group_offset,
                        row_group_limit=row_group_limit, **kw)

        monkeypatch.setattr(tio, "_read_parquet_eager", selective)
        got = []
        for _ in range(10):
            try:
                b = src.poll()
            except ValueError:
                continue
            if b is not None:
                got.append(b)
            if len(got) == 2:
                break
        assert [list(b.columns["x"]) for b in got] == \
            [list(np.arange(4.0)), list(np.arange(8.0, 12.0))]

    def test_read_parquet_row_group_limit(self, tmp_path):
        path = str(tmp_path / "lim.parquet")
        tio.write_parquet(
            tft.frame({"x": np.arange(12.0)}, num_partitions=3), path)
        mid = tio.read_parquet(path, row_group_offset=1,
                               row_group_limit=1)
        assert mid.num_partitions == 1
        np.testing.assert_array_equal(mid.blocks()[0].columns["x"],
                                      np.arange(4.0, 8.0))
        with pytest.raises(ValueError, match="row_group_limit"):
            tio.read_parquet(path, row_group_limit=0)

    def test_background_pump_records_fail_fast_error(self, monkeypatch):
        monkeypatch.setenv("TFT_STREAM_FAIL_FAST", "1")
        h = stream.from_source(
            stream.GeneratorSource(_batches(2))).start(name="ff")
        with faults.inject("batch", fail_n=1, transient=False):
            h.start_background(poll_interval=0.005)
            deadline = time.monotonic() + 10
            while h.error is None and time.monotonic() < deadline:
                time.sleep(0.01)
        assert isinstance(h.error, faults.InjectedFault)
        h.stop()

    def test_fail_fast_env_raises(self, monkeypatch):
        monkeypatch.setenv("TFT_STREAM_FAIL_FAST", "1")
        sf = stream.from_source(stream.GeneratorSource(_batches(2)))
        h = sf.start()
        with faults.inject("batch", fail_n=1, transient=False):
            with pytest.raises(faults.InjectedFault):
                h.run()

    def test_source_schema_drift_skips_and_continues(self):
        def gen():
            yield {"x": np.arange(3.0)}
            yield {"x": np.arange(3, dtype=np.int32)}   # drift
            yield {"x": np.arange(3.0) + 10}

        h = stream.from_source(stream.GeneratorSource(gen())).start()
        h.run()
        m = h.metrics()
        assert m["batches"] == 2 and m["batches_skipped"] == 1
        got = _rows(h.collect_updates())
        assert [r["x"] for r in got] == [0.0, 1.0, 2.0, 10.0, 11.0, 12.0]


# ---------------------------------------------------------------------------
# sinks + metrics + slot-pool composition
# ---------------------------------------------------------------------------

class TestSinksAndMetrics:
    def test_callback_and_collect(self):
        seen = []
        h = stream.from_source(
            stream.GeneratorSource(_batches(3))).start(
                on_update=seen.append)
        h.run()
        assert len(seen) == 3
        assert len(h.collect_updates()) == 3
        assert h.collect_updates() == []         # drained

    def test_callback_error_counted_not_fatal(self):
        def bad(frame):
            raise RuntimeError("sink down")

        h = stream.from_source(
            stream.GeneratorSource(_batches(3))).start(on_update=bad)
        h.run()
        assert h.metrics()["batches"] == 3
        assert tracing.counters.get("stream.sink_errors") == 3

    def test_parquet_sink_appends_and_reads_back(self, tmp_path):
        path = str(tmp_path / "out.parquet")
        sink = stream.ParquetSink(path)
        h = (stream.from_source(stream.GeneratorSource(_batches(6)))
             .group_by("k")
             .aggregate({"v": "sum"}, window=stream.tumbling(2.0),
                        time_col="ts")
             .start(sink=sink))
        h.run()                                  # finalize closes sink
        back = tio.read_parquet(path)
        assert back.schema.names == ["window_start", "k", "v"]
        assert back.count() == 6                 # 3 windows x 2 keys
        assert back.num_partitions == 3          # one row group per emit

    def test_metrics_text_and_dict(self):
        h = (stream.from_source(stream.GeneratorSource(
                 _batches(4, rows=6, keys=3)))
             .group_by("k")
             .aggregate({"v": "sum"}, window=stream.tumbling(2.0),
                        time_col="ts", watermark_delay=1.0)
             .start(name="mx"))
        h.run(max_batches=3)
        text = obs_metrics.metrics_text()
        assert 'tft_stream_batches_total{stream="mx"} 3' in text
        assert 'tft_stream_state_rows{stream="mx"}' in text
        assert 'tft_stream_watermark{stream="mx"}' in text
        m = h.metrics()
        assert m["rows"] == 18 and m["watermark"] == 1.0
        assert m["state_rows"] > 0 and m["state_bytes"] > 0
        assert m["batch_lag_s"] is not None

    def test_stream_leases_serving_slot_pool(self):
        pool = engine_pipeline.SlotPool(2)
        prev = engine_pipeline.install_slot_pool(pool)
        try:
            h = stream.from_source(
                stream.GeneratorSource(_batches(4))).start()
            h.run()
            assert h.metrics()["batches"] == 4
        finally:
            engine_pipeline.install_slot_pool(prev)
        # every lease was returned: both slots acquirable again
        assert pool.try_acquire() and pool.try_acquire()
        pool.release()
        pool.release()

    def test_queue_source_end_to_end_background(self):
        src = stream.QueueSource(Schema.of(x="double"), maxsize=8)
        h = stream.from_source(src) \
            .map_blocks(lambda x: {"y": x + 1.0}) \
            .start(name="bg").start_background(poll_interval=0.005)
        for i in range(5):
            src.put({"x": np.arange(3.0) + i})
        src.close()
        deadline = time.monotonic() + 10
        while not h.done() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h.done()
        assert h.metrics()["batches"] == 5
        h.stop()
