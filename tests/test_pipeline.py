"""Pipelined block execution: ordering, resilience composition, counters.

The streaming engine (``engine/pipeline.py``) keeps a bounded window of
in-flight blocks; these tests prove the contracts the serial engine
promised are preserved under overlap — output ordering at every depth,
drain-time errors re-run synchronously through the retry/OOM-split/
pad-fallback machinery and attributed to the right block, empty blocks
flow through the window, and ``TFT_PIPELINE_DEPTH=1`` is bit-identical
to the serial path. Runs standalone via ``run-tests.sh --pipeline``.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import resilience as rz
from tensorframes_tpu.engine.executor import BlockExecutor
from tensorframes_tpu.engine.pipeline import (PipelinedExecutor,
                                              pipeline_depth, run_pipelined)
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.utils import tracing
from tensorframes_tpu.utils.tracing import counters

pytestmark = pytest.mark.pipeline


@pytest.fixture(autouse=True)
def _fast_and_clean(monkeypatch):
    """Millisecond backoffs + clean counters/faults for every test."""
    monkeypatch.setenv("TFT_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("TFT_RETRY_MAX_DELAY", "0.01")
    monkeypatch.delenv("TFT_PIPELINE_DEPTH", raising=False)
    counters.reset()
    faults.reset()
    yield
    faults.reset()


def _depth(monkeypatch, d):
    monkeypatch.setenv("TFT_PIPELINE_DEPTH", str(d))


def _counters_consistent():
    sub = counters.get("pipeline.submitted")
    drn = counters.get("pipeline.drained")
    fb = counters.get("pipeline.sync_fallbacks")
    assert sub == drn, (sub, drn)
    assert fb <= drn
    return sub


# ---------------------------------------------------------------------------
# depth knob + runner primitives
# ---------------------------------------------------------------------------

class TestDepthKnob:
    def test_default_and_env(self, monkeypatch):
        assert pipeline_depth() == 3
        _depth(monkeypatch, 8)
        assert pipeline_depth() == 8
        assert pipeline_depth(2) == 2  # explicit wins over env

    def test_floor_at_one(self, monkeypatch):
        _depth(monkeypatch, 0)
        assert pipeline_depth() == 1
        assert pipeline_depth(-3) == 1

    def test_malformed_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "many")
        assert pipeline_depth() == 3


class TestRunner:
    def test_window_is_bounded_and_fifo(self):
        """At depth 3, never more than 3 undrained submissions exist and
        results come back in submission order."""
        events = []

        def submit(b):
            events.append(("s", b))
            return b

        def drain(p, b):
            events.append(("d", b))
            return p * 10

        out = run_pipelined(list(range(7)), lambda b: b * 10, submit,
                            drain, depth=3)
        assert out == [b * 10 for b in range(7)]
        in_flight = 0
        peak = 0
        drained = []
        for kind, b in events:
            if kind == "s":
                in_flight += 1
                peak = max(peak, in_flight)
            else:
                in_flight -= 1
                drained.append(b)
        assert peak == 3
        assert drained == sorted(drained)

    def test_depth_one_uses_serial_fn_only(self):
        calls = []
        out = run_pipelined(
            [1, 2, 3],
            lambda b: calls.append(b) or b,
            lambda b: pytest.fail("submit must not run at depth 1"),
            lambda p, b: pytest.fail("drain must not run at depth 1"),
            depth=1)
        assert calls == [1, 2, 3] and out == [1, 2, 3]

    def test_single_block_stream_stays_serial(self):
        out = run_pipelined(
            ["only"],
            lambda b: b.upper(),
            lambda b: pytest.fail("no pipeline for one block"),
            lambda p, b: None,
            depth=4)
        assert out == ["ONLY"]
        assert counters.get("pipeline.submitted") == 0


# ---------------------------------------------------------------------------
# ordering through the ops
# ---------------------------------------------------------------------------

class TestOrdering:
    @pytest.mark.parametrize("depth", [1, 3, 8])
    def test_map_blocks_order_preserved(self, monkeypatch, depth):
        _depth(monkeypatch, depth)
        df = tft.frame({"x": np.arange(40.0)}, num_partitions=6)
        out = df.map_blocks(lambda x: {"y": x * 2.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(40.0) * 2.0)
        if depth > 1:
            assert _counters_consistent() == 6

    @pytest.mark.parametrize("depth", [1, 3, 8])
    def test_map_rows_and_filter_order_preserved(self, monkeypatch, depth):
        _depth(monkeypatch, depth)
        df = tft.frame({"x": np.arange(30.0)}, num_partitions=5)
        out = df.map_rows(lambda x: {"z": x + 0.5}).collect()
        got = np.asarray([r["z"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(30.0) + 0.5)
        kept = df.filter(lambda x: x % 2.0 == 0.0).collect()
        got = np.asarray([r["x"] for r in kept], float).ravel()
        np.testing.assert_array_equal(got, np.arange(0.0, 30.0, 2.0))

    @pytest.mark.parametrize("depth", [1, 3, 8])
    def test_reduce_blocks_partials_pipelined(self, monkeypatch, depth):
        _depth(monkeypatch, depth)
        df = tft.frame({"x": np.arange(24.0)}, num_partitions=4)
        total = df.reduce_blocks(lambda x_input: {"x": x_input.sum()})
        assert float(total) == float(np.arange(24.0).sum())

    def test_depth1_bit_identical_to_depth3(self, monkeypatch):
        rng = np.random.default_rng(7)
        data = rng.standard_normal(101)
        df = tft.frame({"x": data}, num_partitions=7)
        fetch = lambda x: {"y": np.float64(1.0) / (x * x + 0.125)}  # noqa: E731
        _depth(monkeypatch, 3)
        piped = df.map_blocks(fetch).collect()
        _depth(monkeypatch, 1)
        serial = df.map_blocks(fetch).collect()
        a = np.asarray([r["y"] for r in piped])
        b = np.asarray([r["y"] for r in serial])
        assert a.tobytes() == b.tobytes()  # bit-identical, not just close


# ---------------------------------------------------------------------------
# resilience composition under pipelining
# ---------------------------------------------------------------------------

class TestPipelineResilience:
    def test_drain_error_attributed_to_right_block(self, monkeypatch):
        """One injected drain fault: every block's values still come back
        right (a wrong-block re-run would duplicate or drop a partition)
        and exactly one sync fallback is recorded."""
        _depth(monkeypatch, 3)
        df = tft.frame({"x": np.arange(24.0)}, num_partitions=4)
        with faults.inject("drain", fail_n=1):
            out = df.map_blocks(lambda x: {"y": x * 5.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(24.0) * 5.0)
        assert counters.get("pipeline.sync_fallbacks") == 1
        assert _counters_consistent() == 4

    def test_submit_error_defers_to_sync_recovery(self, monkeypatch):
        """A transient fault at the async submit (compile site) re-runs
        that block synchronously; the sync path absorbs further injected
        faults through its retry loop."""
        _depth(monkeypatch, 2)
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        with faults.inject("compile", fail_n=4):
            out = df.map_blocks(lambda x: {"y": x + 2.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(12.0) + 2.0)
        assert counters.get("pipeline.sync_fallbacks") >= 1
        _counters_consistent()

    def test_oom_split_recovers_under_pipelining(self, monkeypatch):
        """OOM faults outlasting the async submits reach the sync
        recovery's dispatch, which splits the block and re-runs the
        halves (map_rows = row-local contract)."""
        _depth(monkeypatch, 2)
        df = tft.frame({"x": np.arange(16.0)}, num_partitions=2)
        with faults.inject("oom", fail_n=3):
            out = df.map_rows(lambda x: {"y": x * 3.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(16.0) * 3.0)
        assert counters.get("oom_split.dispatches") >= 1
        assert counters.get("pipeline.sync_fallbacks") >= 1
        _counters_consistent()

    def test_pad_fallback_recovers_under_pipelining(self, monkeypatch):
        """pad_compile faults outlasting the async submits hit the sync
        recovery's padded path, which falls back to the exact shape."""
        _depth(monkeypatch, 2)
        # 7 and 6-row partitions pad to the 8-bucket
        df = tft.frame({"x": np.arange(13.0)}, num_partitions=2)
        with faults.inject("pad_compile", fail_n=3):
            out = df.map_rows(lambda x: {"y": x + 10.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(13.0) + 10.0)
        assert counters.get("pad_fallback.compiles") >= 1
        assert counters.get("pipeline.sync_fallbacks") >= 1
        _counters_consistent()

    def test_permanent_unpadded_error_reraises_without_rerun(
            self, monkeypatch):
        """A deterministic (non-transient, non-OOM) failure on the
        exact-shape async path re-raises at drain — no duplicate
        execution, no bogus 'recovery' in the fallback counter."""
        _depth(monkeypatch, 2)
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        with faults.inject("dispatch", fail_n=1, transient=False):
            with pytest.raises(rz.InjectedFault):
                df.map_blocks(lambda x: {"y": x + 1.0}).collect()
        assert counters.get("pipeline.sync_fallbacks") == 0

    def test_permanent_padded_error_still_tries_sync_fallback(
            self, monkeypatch):
        """A permanent failure on the PADDED async path must keep the
        sync re-run: its exact-shape fallback can still recover."""
        _depth(monkeypatch, 2)
        # 7/6-row partitions pad to the 8-bucket on the map_rows path
        df = tft.frame({"x": np.arange(13.0)}, num_partitions=2)
        with faults.inject("pad_compile", fail_n=2, transient=False):
            out = df.map_rows(lambda x: {"y": x - 1.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(13.0) - 1.0)
        assert counters.get("pipeline.sync_fallbacks") == 2

    def test_permanent_error_still_raises_at_drain(self, monkeypatch):
        """The sync recovery re-raises genuine failures: a fault armed
        past every recovery attempt propagates out of collect()."""
        monkeypatch.setenv("TFT_RETRY_MAX_ATTEMPTS", "1")
        _depth(monkeypatch, 2)
        df = tft.frame({"x": np.arange(8.0)}, num_partitions=2)
        with faults.inject("dispatch", fail_n=100):
            with pytest.raises(rz.InjectedFault):
                df.map_blocks(lambda x: {"y": x + 1.0}).collect()


# ---------------------------------------------------------------------------
# window edge cases
# ---------------------------------------------------------------------------

class TestWindowEdges:
    def test_empty_blocks_flow_through_window(self, monkeypatch):
        _depth(monkeypatch, 3)
        # 3 rows over 5 partitions -> repartition makes some 0-row blocks
        df = tft.frame({"x": np.arange(3.0)}, num_partitions=1)
        df5 = df.repartition(5)
        assert sum(b.num_rows == 0 for b in df5.blocks()) >= 2
        out = df5.map_blocks(lambda x: {"y": x + 1.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(3.0) + 1.0)
        assert _counters_consistent() == 5

    def test_all_empty_frame(self, monkeypatch):
        _depth(monkeypatch, 3)
        df = tft.frame({"x": np.arange(2.0)}, num_partitions=1)
        empty = df.filter(lambda x: x > 99.0).repartition(4)
        out = empty.map_rows(lambda x: {"y": x * 2.0})
        assert out.count() == 0

    def test_depth_exceeding_block_count(self, monkeypatch):
        _depth(monkeypatch, 64)
        df = tft.frame({"x": np.arange(12.0)}, num_partitions=3)
        out = df.map_blocks(lambda x: {"y": x - 1.0}).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(12.0) - 1.0)
        assert _counters_consistent() == 3


# ---------------------------------------------------------------------------
# PipelinedExecutor + donation + occupancy
# ---------------------------------------------------------------------------

class TestPipelinedExecutor:
    def test_pins_depth_over_env(self, monkeypatch):
        _depth(monkeypatch, 1)  # env says serial...
        pex = PipelinedExecutor(BlockExecutor(), depth=3)
        assert pex.depth == 3
        df = tft.frame({"x": np.arange(20.0)}, num_partitions=4)
        out = df.map_blocks(lambda x: {"y": x * 2.0},
                            executor=pex).collect()
        got = np.asarray([r["y"] for r in out], float).ravel()
        np.testing.assert_array_equal(got, np.arange(20.0) * 2.0)
        # ...but the executor's pinned depth actually pipelined
        assert _counters_consistent() == 4

    def test_map_helper_orders_results(self):
        ex = PipelinedExecutor(BlockExecutor(), depth=2)
        from tensorframes_tpu.engine import ops as _ops
        df = tft.frame({"x": np.arange(4.0)})
        comp = _ops._map_computation(lambda x: {"y": x * 2.0}, df.schema,
                                     block_level=True)
        streams = [{"x": np.full(3, float(i))} for i in range(5)]
        outs = ex.map(streams, comp)
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o["y"], np.full(3, 2.0 * i))

    def test_padded_submit_uses_donating_executable(self, monkeypatch):
        """The padded staging path compiles a donating variant distinct
        from the plain executable (cache keys differ), and both produce
        the same values."""
        ex = BlockExecutor(pad_rows=True)
        from tensorframes_tpu.engine import ops as _ops
        df = tft.frame({"x": np.arange(5.0)})
        comp = _ops._map_computation(lambda x: {"y": x + 1.0}, df.schema,
                                     block_level=True)
        arrays = {"x": np.arange(5.0)}  # pads to the 8-bucket
        monkeypatch.setenv("TFT_DONATE", "1")  # default-off on CPU
        out_async = ex.submit(comp, arrays).drain()
        donating = ex.compile_count
        monkeypatch.setenv("TFT_DONATE", "0")
        out_plain = ex.run(comp, arrays)
        np.testing.assert_array_equal(out_async["y"], out_plain["y"])
        assert ex.compile_count == donating + 1  # distinct executables

    def test_occupancy_gauge_sampled(self, monkeypatch):
        _depth(monkeypatch, 3)
        tracing.timings.reset()
        tracing.enable()
        try:
            df = tft.frame({"x": np.arange(30.0)}, num_partitions=6)
            df.map_blocks(lambda x: {"y": x + 1.0}).blocks()
        finally:
            tracing.disable()
        snap = tracing.timings.snapshot()
        occ = snap.get("pipeline.occupancy")
        assert occ is not None and occ["count"] == 6
        assert occ["max"] <= 3  # never exceeds the window
