"""Device-memory manager suite: budget ledger, spill/fault bit-identity,
LRU ordering, proactive splits, external dsort, larger-than-budget
queries (``docs/memory.md``; ``run-tests.sh --memory`` runs this lane).

Every test that configures a budget goes through the ``mem`` fixture so
the process singleton is always restored — the rest of the suite must
keep running unlimited (zero-cost path).
"""

import threading

import numpy as np
import pytest

import jax
import ml_dtypes

import tensorframes_tpu as tft
from tensorframes_tpu import memory
from tensorframes_tpu.memory import (MemoryManager, SpillableBuffer,
                                     SpillableColumns, external_sort)
from tensorframes_tpu.parallel import distributed as D
from tensorframes_tpu.parallel import mesh as M
from tensorframes_tpu.utils.tracing import counters

from conftest import timing_margin

pytestmark = pytest.mark.memory


@pytest.fixture
def mem():
    """Configure an explicit budget; always restores the env-resolved
    singleton afterwards."""
    def set_limit(nbytes, spill=None):
        return memory.configure(limit_bytes=nbytes, spill=spill)

    yield set_limit
    memory._reset()


def _delta(name):
    """Counter snapshot helper: returns a closure reporting the delta."""
    start = counters.get(name)
    return lambda: counters.get(name) - start


def _rows(df):
    return [tuple(r) for r in df.collect()]


# ---------------------------------------------------------------------------
# ledger basics
# ---------------------------------------------------------------------------

class TestLedger:
    def test_unlimited_is_inactive(self, mem):
        m = mem(0)
        assert not m.limited
        assert memory.active() is None
        # admission collapses to a no-op token
        assert m.reserve(10 ** 12) == 0
        assert m.try_reserve(10 ** 12) == 0
        assert m.headroom() is None
        assert m.would_overflow(10 ** 12) is False

    def test_env_budget_resolution(self, mem, monkeypatch):
        monkeypatch.setenv("TFT_MEM_LIMIT_BYTES", "12345")
        memory._reset()
        m = memory.manager()
        assert m.limit == 12345
        assert memory.active() is m

    def test_reserve_release_accounting(self, mem):
        m = mem(1000)
        t1 = m.reserve(400, op="t")
        t2 = m.reserve(400, op="t")
        assert m.snapshot()["inflight_bytes"] == 800
        assert m.try_reserve(400) is None  # over budget, nothing to spill
        m.release(t1)
        t3 = m.try_reserve(400)
        assert t3 == 400
        m.release(t2)
        m.release(t3)
        assert m.snapshot()["inflight_bytes"] == 0

    def test_would_overflow_is_whole_budget(self, mem):
        m = mem(1000)
        assert m.would_overflow(1001)
        assert not m.would_overflow(1000)

    def test_soft_admission_counts_overflow(self, mem, monkeypatch):
        monkeypatch.setenv("TFT_MEM_ADMIT_WAIT_S", "0.05")
        m = mem(1000)
        hold = m.reserve(900)
        over = _delta("memory.overflow_admissions")
        waits = _delta("memory.admission_waits")
        tok = m.reserve(900, op="t")  # cannot fit: waits, then admits over
        assert tok == 900
        assert over() == 1
        assert waits() == 1
        m.release(hold)
        m.release(tok)

    @pytest.mark.timing
    def test_impossible_request_overflows_without_stalling(self, mem,
                                                           monkeypatch):
        import time
        # nbytes > limit can never fit: reserve must overflow-admit
        # immediately, not burn the whole admission-wait budget (the
        # wall-clock bound makes this timing-marked: PR 13 audit)
        monkeypatch.setenv("TFT_MEM_ADMIT_WAIT_S", "5.0")
        m = mem(1000)
        over = _delta("memory.overflow_admissions")
        t0 = time.monotonic()
        tok = m.reserve(2000, op="t")
        assert time.monotonic() - t0 < timing_margin(1.0)
        assert tok == 2000
        assert over() == 1
        m.release(tok)

    @pytest.mark.timing
    def test_admission_wait_is_bounded(self, mem, monkeypatch):
        import time
        monkeypatch.setenv("TFT_MEM_ADMIT_WAIT_S", "0.2")
        m = mem(1000)
        hold = m.reserve(1000)
        t0 = time.monotonic()
        tok = m.reserve(500, op="t")
        took = time.monotonic() - t0
        assert took < timing_margin(3.0)
        assert took >= 0.15
        m.release(hold)
        m.release(tok)

    def test_admission_unblocks_on_release(self, mem, monkeypatch):
        monkeypatch.setenv("TFT_MEM_ADMIT_WAIT_S", "5.0")
        m = mem(1000)
        hold = m.reserve(900)
        over = _delta("memory.overflow_admissions")
        got = []

        def admit():
            got.append(m.reserve(500, op="t"))

        t = threading.Thread(target=admit)
        t.start()
        m.release(hold)
        t.join(timeout=timing_margin(10.0))
        assert not t.is_alive()
        assert got == [500]
        assert over() == 0  # a clean admission, not an overflow
        m.release(500)


# ---------------------------------------------------------------------------
# spill / fault bit-identity
# ---------------------------------------------------------------------------

SPILL_DTYPES = [np.float32, np.float64, np.int32, np.int64, np.uint32,
                np.bool_, ml_dtypes.bfloat16]


class TestSpillFault:
    @pytest.mark.parametrize("dtype", SPILL_DTYPES,
                             ids=[np.dtype(d).name for d in SPILL_DTYPES])
    def test_round_trip_bit_identity(self, dtype, rng):
        raw = rng.standard_normal(257) * 100
        host = raw.astype(dtype)
        dev = jax.device_put(host)
        buf = SpillableBuffer("t", {"x": dev})
        nbytes = buf.mem_device_bytes()
        assert nbytes == host.nbytes
        freed = buf.spill()
        assert freed == nbytes and buf.spilled
        assert buf.mem_device_bytes() == 0
        assert buf.mem_host_bytes() == nbytes
        back = buf.get("x")  # faults the buffer back
        assert not buf.spilled
        out = np.asarray(back)
        assert out.dtype == host.dtype
        # BIT identity, not value closeness
        np.testing.assert_array_equal(out.view(np.uint8),
                                      host.view(np.uint8))

    def test_string_ride_along_untouched(self):
        s = np.array(["a", "bb", None], object)
        dev = jax.device_put(np.arange(3.0))
        buf = SpillableBuffer("t", {"x": dev, "s": s})
        buf.spill()
        assert buf.mem_device_bytes() == 0
        got = buf.arrays()
        assert got["s"] is s  # never copied, never converted
        np.testing.assert_array_equal(np.asarray(got["x"]),
                                      np.arange(3.0))

    def test_double_spill_and_fault_are_idempotent(self):
        buf = SpillableBuffer("t", {"x": jax.device_put(np.arange(8.0))})
        assert buf.spill() > 0
        assert buf.spill() == 0
        assert buf.fault() > 0
        assert buf.fault() == 0

    def test_spillable_columns_transparent_access(self, mem):
        m = mem(10 ** 9)
        cols = {"x": jax.device_put(np.arange(16.0)),
                "s": np.array(list("abcdefghijklmnop"), object)}
        sc = memory.spillable_columns("t", cols, m)
        assert isinstance(sc, SpillableColumns)
        faults = _delta("memory.faults")
        sc.mem_spill()
        assert sc.mem_is_spilled()
        # any access faults the mapping back, through the manager
        np.testing.assert_array_equal(np.asarray(sc["x"]),
                                      np.arange(16.0))
        assert not sc.mem_is_spilled()
        assert faults() == 1

    def test_spillable_columns_host_value_does_not_fault(self, mem):
        m = mem(10 ** 9)
        sc = memory.spillable_columns(
            "t", {"x": jax.device_put(np.arange(16.0))}, m)
        sc.mem_spill()
        np.testing.assert_array_equal(sc.host_value("x"), np.arange(16.0))
        assert sc.mem_is_spilled()  # still spilled

    def test_inactive_manager_returns_plain_dict(self, mem):
        mem(0)
        cols = {"x": jax.device_put(np.arange(4.0))}
        out = memory.spillable_columns("t", cols)
        assert type(out) is dict


# ---------------------------------------------------------------------------
# LRU ordering under pressure
# ---------------------------------------------------------------------------

class TestLRU:
    def _buf(self, name, n=100):
        return SpillableBuffer(
            name, {"x": jax.device_put(np.arange(n, dtype=np.float64))})

    def test_cold_entry_spills_first(self, mem):
        m = mem(3000)  # three 800 B buffers fit
        a, b, c = self._buf("a"), self._buf("b"), self._buf("c")
        for buf in (a, b, c):
            m.register(buf)
        m.touch(a)  # a is now hottest; b is the coldest
        tok = m.reserve(2000, op="t")  # needs two spills
        assert b.spilled and c.spilled
        assert not a.spilled
        m.release(tok)

    def test_registration_over_budget_spills_immediately(self, mem):
        m = mem(1000)
        spills = _delta("memory.spills")
        a, b = self._buf("a"), self._buf("b")
        m.register(a)
        m.register(b)  # 1600 B resident > 1000: the LRU one spills
        assert a.spilled and not b.spilled
        assert spills() == 1

    def test_fault_back_spills_others(self, mem):
        m = mem(1000)
        a, b = self._buf("a"), self._buf("b")
        m.register(a)
        m.register(b)
        assert a.spilled
        m.touch(a)  # faulting a back must push b out
        assert not a.spilled and b.spilled

    def test_dead_entries_are_pruned(self, mem):
        m = mem(10 ** 6)
        buf = self._buf("a")
        m.register(buf)
        assert m.snapshot()["resident_buffers"] == 1
        del buf
        import gc
        gc.collect()
        assert m.snapshot()["resident_buffers"] == 0

    def test_drop_releases_entry(self, mem):
        m = mem(10 ** 6)
        buf = self._buf("a")
        m.register(buf)
        m.drop(buf)
        assert m.snapshot()["resident_buffers"] == 0


# ---------------------------------------------------------------------------
# executor integration: proactive splits, sync dispatch, window spill
# ---------------------------------------------------------------------------

class TestExecutorAdmission:
    def test_proactive_split_before_dispatch(self, mem):
        mem(4096)
        proactive = _delta("memory.proactive_splits")
        oom = _delta("oom_split.dispatches")
        df = tft.frame({"x": np.arange(4096, dtype=np.float64)})
        out = df.map_rows(lambda x: {"z": x + 1.0})
        z = np.concatenate([np.asarray(b.columns["z"])
                            for b in out.blocks()])
        np.testing.assert_array_equal(z, np.arange(4096.0) + 1.0)
        assert proactive() > 0
        assert oom() == 0  # split BEFORE the allocator, not after

    def test_pipeline_pressure_falls_back_to_sync(self, mem, monkeypatch):
        # window of 64 KiB blocks against a 100 KiB budget: the async
        # submit path cannot hold depth x est in flight and must run
        # some blocks synchronously (admitted) instead of blocking
        monkeypatch.setenv("TFT_PIPELINE_DEPTH", "4")
        mem(100 * 1024)
        sync = _delta("memory.sync_dispatches")
        df = tft.frame({"x": np.arange(32768, dtype=np.float64)},
                       num_partitions=4)
        out = df.map_blocks(lambda x: {"z": x * 2.0})
        z = np.concatenate([np.asarray(b.columns["z"])
                            for b in out.blocks()])
        np.testing.assert_array_equal(z, np.arange(32768.0) * 2.0)
        assert sync() > 0

    def test_unlimited_run_reserves_nothing(self, mem):
        mem(0)
        waits = _delta("memory.admission_waits")
        spills = _delta("memory.spills")
        df = tft.frame({"x": np.arange(10000.0)}, num_partitions=4)
        df.map_blocks(lambda x: {"z": x + 1.0}).blocks()
        assert waits() == 0 and spills() == 0

    def test_pending_block_is_spill_candidate(self, mem):
        from tensorframes_tpu.engine.executor import BlockExecutor
        from tensorframes_tpu.computation import Computation, TensorSpec
        from tensorframes_tpu.shape import Shape, Unknown
        from tensorframes_tpu import dtypes as _dt

        m = mem(10 ** 6)
        comp = Computation.trace(
            lambda x: {"z": x + 1.0},
            [TensorSpec("x", _dt.double, Shape(Unknown))])
        ex = BlockExecutor()
        arrays = {"x": np.arange(64, dtype=np.float64)}
        pending = ex.submit(comp, arrays, pad_ok=False)
        assert m.snapshot()["resident_buffers"] == 1
        # a ledger spill early-drains the device output to host
        assert m.make_room(10 ** 6)
        assert pending.mem_is_spilled()
        out = pending.drain()
        np.testing.assert_array_equal(out["z"], np.arange(64.0) + 1.0)
        assert m.snapshot()["resident_buffers"] == 0


# ---------------------------------------------------------------------------
# external sort
# ---------------------------------------------------------------------------

class TestExternalSort:
    def _cols(self, rng, n=5000):
        return {"k": rng.integers(0, 200, n).astype(np.int64),
                "v": rng.random(n)}

    @pytest.mark.parametrize("descending", [False, True])
    def test_matches_stable_inmemory_sort(self, rng, descending, mem):
        m = mem(16 * 1024)
        cols = self._cols(rng)
        out, order, stats = external_sort(cols, ["k"],
                                          descending=descending,
                                          manager=m)
        assert stats["runs"] > 1
        key = -cols["k"] if descending else cols["k"]
        ref = np.argsort(key, kind="stable")
        np.testing.assert_array_equal(order, ref)
        np.testing.assert_array_equal(out["k"], cols["k"][ref])
        np.testing.assert_array_equal(out["v"], cols["v"][ref])

    def test_multi_key_lexicographic(self, rng, mem):
        m = mem(16 * 1024)
        n = 4000
        cols = {"a": rng.integers(0, 8, n).astype(np.int64),
                "b": rng.integers(0, 8, n).astype(np.int64),
                "v": rng.random(n)}
        out, order, _ = external_sort(cols, ["a", "b"], manager=m)
        ref = np.lexsort((cols["b"], cols["a"]))
        np.testing.assert_array_equal(order, ref)
        np.testing.assert_array_equal(out["v"], cols["v"][ref])

    def test_nan_keys_sort_last(self, rng, mem):
        m = mem(8 * 1024)
        n = 3000
        k = rng.random(n)
        k[rng.integers(0, n, 50)] = np.nan
        cols = {"k": k, "v": np.arange(n, dtype=np.float64)}
        out, order, _ = external_sort(cols, ["k"], manager=m)
        ref = np.argsort(k, kind="stable")  # numpy puts NaN last too
        np.testing.assert_array_equal(order, ref)

    def test_counts_run_spills(self, rng, mem):
        m = mem(16 * 1024)
        spills = _delta("memory.spills")
        _, _, stats = external_sort(self._cols(rng), ["k"], manager=m)
        assert spills() >= stats["runs"]

    def test_empty_input(self, mem):
        m = mem(1024)
        out, order, stats = external_sort(
            {"k": np.empty(0, np.int64)}, ["k"], manager=m)
        assert len(order) == 0 and stats["runs"] == 0


# ---------------------------------------------------------------------------
# external dsort vs in-memory dsort
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh4():
    return M.local_mesh(4)


class TestExternalDsort:
    def _frame(self, rng, n=8192):
        return tft.frame(
            {"k": rng.integers(0, 500, n).astype(np.int64),
             "v": rng.random(n)}, num_partitions=4)

    def test_equals_inmemory_dsort(self, rng, mesh4, mem):
        df = self._frame(rng)
        mem(0)
        ref = _rows(D.dsort("k", D.distribute(df, mesh4)).collect_frame())
        mem(32 * 1024)  # frame is 128 KiB of device columns
        ext = _delta("memory.external_sorts")
        got = _rows(D.dsort("k", D.distribute(df, mesh4)).collect_frame())
        assert ext() == 1  # the external path actually ran
        assert got == ref

    def test_descending_equals_inmemory(self, rng, mesh4, mem):
        df = self._frame(rng)
        mem(0)
        ref = _rows(D.dsort("v", D.distribute(df, mesh4),
                            descending=True).collect_frame())
        mem(32 * 1024)
        got = _rows(D.dsort("v", D.distribute(df, mesh4),
                            descending=True).collect_frame())
        assert got == ref

    def test_string_ride_along_permutes(self, rng, mesh4, mem):
        n = 4096
        df = tft.frame(
            {"k": rng.integers(0, 97, n).astype(np.int64),
             "s": np.array([f"row{i}" for i in range(n)], object)},
            num_partitions=4)
        mem(0)
        ref = _rows(D.dsort("k", D.distribute(df, mesh4)).collect_frame())
        mem(8 * 1024)
        got = _rows(D.dsort("k", D.distribute(df, mesh4)).collect_frame())
        assert got == ref

    def test_under_threshold_keeps_columnsort(self, rng, mesh4, mem):
        mem(10 ** 9)  # limited, but the frame fits comfortably
        ext = _delta("memory.external_sorts")
        df = self._frame(rng, n=512)
        D.dsort("k", D.distribute(df, mesh4)).collect_frame()
        assert ext() == 0

    def test_invalid_key_still_raises(self, rng, mesh4, mem):
        mem(8 * 1024)
        dist = D.distribute(self._frame(rng), mesh4)
        with pytest.raises(KeyError):
            D.dsort("nope", dist)

    def test_spilled_frame_collects_without_faulting(self, rng, mesh4,
                                                     mem):
        # the PR's core promise: a larger-than-budget frame collects
        # from its pinned host buffers — shape metadata (padded_rows /
        # valid_row_mask) and host reads must never fault it back
        m = mem(16 * 1024)
        df = self._frame(rng)  # 128 KiB of device columns
        dist = D.distribute(df, mesh4)
        assert dist.columns.mem_is_spilled()  # registration spilled it
        faults = _delta("memory.faults")
        assert dist.padded_rows == 8192
        out = dist.collect_frame()
        assert out.count() == 8192
        assert faults() == 0, \
            "collect_frame re-resident a spilled frame"
        assert dist.columns.mem_is_spilled()

    def test_dmap_result_copies_through_accessors(self, rng, mesh4,
                                                  mem):
        # dict(dist.columns) would raw-copy a spilled mapping's None
        # placeholders; the per-key copy faults back and stays correct
        m = mem(10 ** 9)
        dist = D.distribute(self._frame(rng, n=512), mesh4)
        dist.columns.mem_spill()
        out = D.dmap_blocks(lambda v: {"z": v + 1.0}, dist)
        got = out.collect_frame()
        assert got.count() == 512
        assert all(c is not None for c in
                   (out.columns[n] for n in out.columns))


# ---------------------------------------------------------------------------
# the acceptance bar: a frame 4x the budget completes the relational
# suite bit-identical, with spills and zero allocator OOMs
# ---------------------------------------------------------------------------

class TestLargerThanBudget:
    def test_relational_suite_4x_limit(self, rng, mesh4, mem):
        n = 16384  # 2 f64 columns = 256 KiB
        df = tft.frame(
            {"k": rng.integers(0, 100, n).astype(np.float64),
             "v": rng.random(n)}, num_partitions=8)

        def suite():
            mapped = df.map_blocks(lambda v: {"mv": v * 2.0})
            filtered = mapped.filter(lambda k: k < 50.0)
            map_rows = [tuple(r) for r in filtered.collect()]
            agg = tft.aggregate({"v": "sum"}, df.group_by("k"))
            agg_rows = [tuple(r) for r in agg.collect()]
            dist = D.distribute(df, mesh4)
            sort_rows = _rows(D.dsort("k", dist).collect_frame())
            red = tft.reduce_blocks(
                lambda v_input: {"v": v_input.sum()}, df)
            if isinstance(red, dict):
                red = red["v"]
            return map_rows, agg_rows, sort_rows, float(np.asarray(red))

        mem(0)
        ref = suite()
        mem(64 * 1024)  # the frame is 4x this budget
        spills = _delta("memory.spills")
        oom = _delta("oom_split.dispatches")
        got = suite()
        assert got[0] == ref[0], "map/filter diverged under the budget"
        assert got[1] == ref[1], "aggregate diverged under the budget"
        assert got[2] == ref[2], "dsort diverged under the budget"
        assert got[3] == pytest.approx(ref[3], rel=1e-12)
        assert spills() > 0, "a 4x-budget run must spill"
        assert oom() == 0, "zero allocator OOMs: the ledger acts first"


# ---------------------------------------------------------------------------
# serve integration: unforced estimates + ledger headroom
# ---------------------------------------------------------------------------

class TestServeIntegration:
    def test_unforced_frame_gets_estimate(self, mem):
        from tensorframes_tpu.serve.scheduler import _estimate
        df = tft.frame({"x": np.arange(512.0)})
        lazy = df.map_blocks(lambda x: {"z": x + 1.0})
        rows, nbytes = _estimate(lazy)
        assert rows == 512.0
        assert nbytes == 512 * 8 * 2  # x + z, f64
        # forced stays exact
        lazy.blocks()
        rows2, nbytes2 = _estimate(lazy)
        assert rows2 == 512.0 and nbytes2 == nbytes

    def test_filter_estimate_is_upper_bound(self, mem):
        df = tft.frame({"x": np.arange(512.0)})
        f = df.filter(lambda x: x < 0.0)
        assert f.estimated_rows() == 512  # bound, not truth
        f.blocks()
        assert f.estimated_rows() == 0  # exact once forced

    def test_ledger_headroom_backs_admission(self, mem):
        m = mem(10000)
        from tensorframes_tpu.serve.scheduler import QueryScheduler
        sched = QueryScheduler(workers=0, name="memtest")
        try:
            assert sched._hbm_headroom() == int(10000 * 0.9)
            tok = m.reserve(5000)
            assert sched._hbm_headroom() == int(10000 * 0.9) - 5000
            m.release(tok)
        finally:
            sched.close()

    def test_larger_than_budget_query_admits_spill_aware(self, mem):
        # the engine executes a 4x-budget frame out-of-core, so the
        # ledger-backed admission must compare the streaming working
        # set (~one block), not the whole frame — and serve it
        mem(64 * 1024)
        from tensorframes_tpu.serve.scheduler import QueryScheduler
        df = tft.frame({"x": np.arange(32768, dtype=np.float64)},
                       num_partitions=16)  # 256 KiB, blocks of 16 KiB
        with QueryScheduler(workers=1, name="memadmit") as sched:
            fut = sched.submit(df.map_blocks(lambda x: {"z": x + 1.0}),
                               tenant="t")
            out = fut.result(timeout=timing_margin(60.0))
            assert out.count() == 32768
            assert fut.state == "done"

    def test_no_budget_headroom_is_none(self, mem):
        mem(0)
        from tensorframes_tpu.serve.scheduler import QueryScheduler
        sched = QueryScheduler(workers=0, name="memtest2")
        try:
            assert sched._hbm_headroom() is None
        finally:
            sched.close()


# ---------------------------------------------------------------------------
# stream state: spill instead of force-evict
# ---------------------------------------------------------------------------

class TestStreamStateSpill:
    def _run_stream(self, cap):
        from tensorframes_tpu.stream import GeneratorSource, StreamingFrame
        from tensorframes_tpu.stream.aggregate import tumbling

        def batches():
            for i in range(6):
                yield {"t": np.full(8, float(i)),
                       "k": np.arange(8, dtype=np.int64),
                       "v": np.full(8, 1.0)}

        sf = StreamingFrame(GeneratorSource(batches()))
        agg = sf.group_by("k").aggregate(
            {"v": "sum"}, window=tumbling(2.0), time_col="t",
            watermark_delay=10.0,  # nothing emits by watermark
            max_state_rows=cap)
        h = agg.start()
        h.run()
        frames = h.collect_updates()
        rows = sorted(tuple(map(float, r))
                      for f in frames for r in f.collect())
        return agg, rows

    def test_spills_and_keeps_windows_live(self, mem):
        mem(0)
        agg_ref, ref = self._run_stream(cap=10 ** 9)  # uncapped truth
        mem(10 ** 9)
        agg, got = self._run_stream(cap=8)
        assert agg.state_spills > 0
        assert agg.state_evictions == 0, \
            "with a memory manager the cap spills, never force-emits"
        assert got == ref  # results identical to the uncapped run
        assert agg.state_faults == 0  # no window was touched twice here

    def test_spilled_window_faults_back_on_late_fold(self, mem):
        from tensorframes_tpu.stream import GeneratorSource, StreamingFrame
        from tensorframes_tpu.stream.aggregate import tumbling

        def batches():
            # window 0 fills, window 2 pushes it out (spill), then more
            # rows for window 0 arrive -> fault-back + fold
            yield {"t": np.full(8, 0.0),
                   "k": np.arange(8, dtype=np.int64),
                   "v": np.full(8, 1.0)}
            yield {"t": np.full(8, 2.0),
                   "k": np.arange(8, dtype=np.int64),
                   "v": np.full(8, 1.0)}
            yield {"t": np.full(8, 0.5),
                   "k": np.arange(8, dtype=np.int64),
                   "v": np.full(8, 2.0)}

        mem(10 ** 9)
        sf = StreamingFrame(GeneratorSource(batches()))
        agg = sf.group_by("k").aggregate(
            {"v": "sum"}, window=tumbling(2.0), time_col="t",
            watermark_delay=10.0, max_state_rows=8)
        h = agg.start()
        h.run()
        assert agg.state_spills > 0
        assert agg.state_faults > 0
        rows = sorted(tuple(map(float, r))
                      for f in h.collect_updates() for r in f.collect())
        # window 0: v = 1 + 2 = 3 per key; window 2: v = 1 per key
        w0 = [r for r in rows if r[0] == 0.0]
        assert all(r[2] == 3.0 for r in w0) and len(w0) == 8

    def test_without_budget_force_evicts_as_before(self, mem):
        mem(0)
        agg, _ = self._run_stream(cap=8)
        assert agg.state_evictions > 0
        assert agg.state_spills == 0


# ---------------------------------------------------------------------------
# frame cache accounting + metrics + explain
# ---------------------------------------------------------------------------

class TestObservability:
    def test_frame_cache_gauge_and_uncache(self, mem):
        m = mem(10 ** 9)
        df = tft.frame({"x": np.arange(1000.0)})
        df.blocks()
        assert m.frame_cache_bytes() == 8000
        df.uncache()
        assert m.frame_cache_bytes() == 0
        assert df._cache is None

    def test_metrics_families_present(self, mem):
        mem(4096)
        from tensorframes_tpu.observability.metrics import metrics_text
        tft.frame({"x": np.arange(2048.0)}).map_rows(
            lambda x: {"z": x + 1.0}).blocks()
        text = metrics_text()
        for family in ("tft_memory_budget_bytes",
                       "tft_memory_inflight_bytes",
                       "tft_memory_spilled_bytes",
                       "tft_memory_spills_total",
                       "tft_memory_proactive_splits_total"):
            assert family in text, family

    def test_explain_renders_spill_line(self, rng, mesh4, mem):
        from tensorframes_tpu.utils import tracing
        from tensorframes_tpu.observability import last_query_report
        mem(32 * 1024)
        df = tft.frame(
            {"k": rng.integers(0, 50, 8192).astype(np.int64),
             "v": rng.random(8192)}, num_partitions=4)
        tracing.enable()
        try:
            D.dsort("k", D.distribute(df, mesh4))
            report = last_query_report()
        finally:
            tracing.disable()
        assert "spill" in report
        assert "external sort" in report

    def test_proactive_split_event_in_trace(self, mem):
        from tensorframes_tpu.utils import tracing
        mem(4096)
        df = tft.frame({"x": np.arange(4096, dtype=np.float64)})
        out = df.map_rows(lambda x: {"z": x + 1.0})
        tracing.enable()
        try:
            out.blocks()
            trace = out._trace
        finally:
            tracing.disable()
        assert trace is not None
        assert trace.summary()["proactive_splits"] > 0


# ---------------------------------------------------------------------------
# zero-cost when unlimited
# ---------------------------------------------------------------------------

class TestZeroCostUnlimited:
    def test_active_is_none_without_budget(self, mem):
        mem(0)
        assert memory.active() is None

    def test_no_ledger_traffic_in_relational_suite(self, rng, mem):
        mem(0)
        before = {k: counters.get(k) for k in
                  ("memory.spills", "memory.faults",
                   "memory.admission_waits", "memory.sync_dispatches",
                   "memory.proactive_splits")}
        df = tft.frame({"k": rng.integers(0, 10, 1000).astype(np.int64),
                        "v": rng.random(1000)}, num_partitions=4)
        df.map_blocks(lambda v: {"z": v + 1.0}).filter(
            lambda z: z > 0.5).blocks()
        tft.aggregate({"v": "sum"}, df.group_by("k")).blocks()
        for k, v in before.items():
            assert counters.get(k) == v, k

    def test_bypass_context(self, mem):
        m = mem(1000)
        assert memory.active() is m
        with memory.bypass():
            assert memory.active() is None
        assert memory.active() is m
