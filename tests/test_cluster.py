"""Multi-process (2-host analogue) cluster tests.

ONE pair of real processes joins via ``jax.distributed`` on the CPU
backend (4 virtual devices each → one 8-device global mesh) and runs the
distributed surface as named steps (``cluster_worker.py``); each step's
per-worker pass/fail marker becomes its own pytest test here, so a
failure names the op (VERDICT r4 weak #6: the old monolith reported one
3000-char tail). This is the executor-JVM test of the reference
(``DebugRowOpsSuite`` against local Spark executors) at real process
granularity — the subprocess pair is spawned once per session, like the
reference's shared ``local[1]`` Spark fixture.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "cluster_worker.py")

from cluster_worker import STEP_NAMES as STEPS  # noqa: E402  one source


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


class ClusterRun:
    """Parsed outcome of the worker pair: per-(worker, step) verdicts."""

    def __init__(self, returncodes, outputs):
        self.returncodes = returncodes
        self.outputs = outputs
        self.steps = {}  # (pid, step) -> "OK" | "FAIL" | "SKIP"
        for pid, out in enumerate(outputs):
            for m in re.finditer(r"STEP (\w+) (OK|FAIL|SKIP)", out or ""):
                self.steps[(pid, m.group(1))] = m.group(2)

    def first_failure(self, pid: int):
        out = self.outputs[pid] or ""
        m = re.search(r"STEP (\w+) FAIL", out)
        return m.group(1) if m else None

    def step_detail(self, pid: int, step: str) -> str:
        """The worker's output from this step's FAIL marker to the next
        marker — the step-focused traceback."""
        out = self.outputs[pid] or ""
        m = re.search(rf"\[worker {pid}\] STEP {step} FAIL\n(.*?)"
                      rf"(?=\[worker {pid}\] STEP |\Z)", out, re.S)
        return m.group(1) if m else out[-3000:]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    ckpt_dir = str(tmp_path_factory.mktemp("cluster") / "ckpt")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), "2", str(port), ckpt_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("cluster workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    return ClusterRun([p.returncode for p in procs], outs)


@pytest.mark.slow
@pytest.mark.parametrize("step", STEPS)
def test_cluster_step(cluster, step):
    for pid in range(2):
        verdict = cluster.steps.get((pid, step))
        assert verdict is not None, (
            f"worker {pid} never reported step {step!r} (worker died "
            f"earlier? rc={cluster.returncodes[pid]})\n"
            f"{(cluster.outputs[pid] or '')[-2000:]}")
        if verdict == "SKIP":
            # aborted after an earlier failure (collective lockstep);
            # inconclusive here — the failing step's own test reports it
            pytest.skip(
                f"worker {pid} skipped {step!r} after step "
                f"{cluster.first_failure(pid)!r} failed")
        assert verdict == "OK", (
            f"step {step!r} failed on worker {pid}:\n"
            f"{cluster.step_detail(pid, step)}")


@pytest.mark.slow
def test_cluster_workers_exit_clean(cluster):
    # rc is the OR of all steps; catches failures outside any step too
    for pid, rc in enumerate(cluster.returncodes):
        assert rc == 0, (
            f"worker {pid} rc={rc}\n{(cluster.outputs[pid] or '')[-3000:]}")
