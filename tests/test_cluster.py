"""Multi-process (2-host analogue) cluster test.

Spawns two REAL processes that join via ``jax.distributed`` on the CPU
backend (4 virtual devices each → one 8-device global mesh) and run the
full distributed surface end-to-end; see ``cluster_worker.py`` for what
each process asserts. This is the executor-JVM test of the reference
(``DebugRowOpsSuite`` running against local Spark executors) at real
process granularity.
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "cluster_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    ckpt_dir = str(tmp_path / "cluster_ckpt")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), "2", str(port), ckpt_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("cluster workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n{out[-3000:]}")
        assert f"[worker {pid}] OK" in out
