"""DSL tests: construction, naming/scoping, lowering, end-to-end ops, and
golden conformance against the JAX front end (the ExtractNodes oracle
analogue — reference ``dsl/BasicSuite.scala``, ``DSLOperationsSuite.scala``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import dsl
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.dsl import lower as dsl_lower
from tensorframes_tpu.shape import Shape, Unknown


@pytest.fixture(autouse=True)
def fresh_graph():
    # GraphScoping.testGraph analogue: isolate naming counters per test
    with dsl.with_graph():
        yield


# ---------------------------------------------------------------------------
# construction / naming
# ---------------------------------------------------------------------------

def test_tf_convention_name_dedup():
    x = dsl.placeholder("double", Shape(Unknown), name="x")
    a = x + 1.0
    b = x + 2.0
    assert a.name == "Add"
    assert b.name == "Add_1"


def test_scope_prefixes():
    x = dsl.placeholder("double", Shape(Unknown), name="x")
    with dsl.scope("layer"):
        y = x + 1.0
        with dsl.scope("inner"):
            z = y * 2.0
    assert y.name == "layer/Add"
    assert z.name == "layer/inner/Mul"


def test_named_rename():
    x = dsl.placeholder("double", Shape(Unknown), name="x")
    z = (x + 3.0).named("z")
    assert z.name == "z"


def test_with_graph_resets_counters():
    with dsl.with_graph():
        a = dsl.constant(1.0) + dsl.constant(2.0)
        assert a.name == "Add"
    with dsl.with_graph():
        b = dsl.constant(1.0) + dsl.constant(2.0)
        assert b.name == "Add"


def test_shape_and_dtype_inference():
    x = dsl.placeholder("double", Shape(Unknown, 3), name="x")
    y = x + dsl.constant(np.ones(3))
    assert y.shape == Shape(Unknown, 3)
    assert y.dtype is dt.double
    s = dsl.reduce_sum(x, axis=0)
    assert s.shape == Shape(3)
    with pytest.raises(ValueError, match="out of range"):
        dsl.reduce_sum(x, axis=5)


def test_widening_int_plus_double():
    n = dsl.placeholder("int", Shape(Unknown), name="n")
    z = n + 1.5
    assert z.dtype is dt.double


def test_fill_zeros_ones():
    f = dsl.fill((2, 2), 3.0)
    assert f.shape == Shape(2, 2)
    z = dsl.zeros((3,))
    o = dsl.ones((3,), dtype="int")
    assert z.dtype is dt.double and o.dtype is dt.int32
    with pytest.raises(ValueError, match="concrete"):
        dsl.fill(Shape(Unknown), 1.0)


# ---------------------------------------------------------------------------
# end-to-end through the engine
# ---------------------------------------------------------------------------

def test_dsl_map_blocks_readme_scala_example():
    # README.md:154-172 (Scala DSL mapBlocks a + 3.0)
    df = tft.frame({"x": np.arange(10.0)}, num_partitions=2)
    x = tft.block(df, "x")
    z = (x + 3.0).named("z")
    out = df.map_blocks(z)
    assert [r["z"] for r in out.collect()] == [i + 3.0 for i in range(10)]


def test_dsl_map_blocks_trim():
    df = tft.frame({"x": np.arange(4.0)})
    x = tft.block(df, "x")
    z = dsl.identity(x).named("z")
    out = df.map_blocks(z, trim=True)
    assert out.columns == ["z"]
    assert out.count() == 4


def test_dsl_map_rows():
    df = tft.frame({"x": np.arange(5.0)})
    x = tft.row(df, "x")
    z = (x * x).named("z")
    assert [r["z"] for r in df.map_rows(z).collect()] == \
        [i * i for i in range(5)]


def test_dsl_reduce_blocks_sum():
    # README reduce example via DSL: placeholder x_input of rank 1
    df = tft.frame({"x": np.arange(10.0)}, num_partitions=3)
    x_input = dsl.placeholder("double", Shape(Unknown), name="x_input")
    x = dsl.reduce_sum(x_input, axis=0).named("x")
    assert tft.reduce_blocks(x, df) == pytest.approx(45.0)


def test_dsl_reduce_rows_pairwise():
    df = tft.frame({"x": np.arange(6.0)}, num_partitions=2)
    x1 = dsl.placeholder("double", Shape.empty, name="x_1")
    x2 = dsl.placeholder("double", Shape.empty, name="x_2")
    x = (x1 + x2).named("x")
    assert tft.reduce_rows(x, df) == pytest.approx(15.0)


def test_dsl_aggregate():
    df = tft.frame({"key": np.array([0, 0, 1], np.int64),
                    "x": np.array([1.0, 2.0, 10.0])})
    x_input = dsl.placeholder("double", Shape(Unknown), name="x_input")
    x = dsl.reduce_sum(x_input, axis=0).named("x")
    rows = sorted(tft.aggregate(x, df.group_by("key")).collect())
    assert [(r["key"], r["x"]) for r in rows] == [(0, 3.0), (1, 10.0)]


def test_dsl_duplicate_explicit_names_deduped():
    # TF convention: a second request for name "z" yields "z_1" — duplicate
    # fetch columns cannot arise within one graph
    df = tft.frame({"x": np.arange(3.0)})
    x = tft.block(df, "x")
    a = (x + 1.0).named("z")
    b = (x + 2.0).named("z")
    assert (a.name, b.name) == ("z", "z_1")
    out = df.map_blocks([a, b])
    assert out.columns == ["x", "z", "z_1"]


def test_block_placeholder_lead_is_unknown():
    # even a concrete frame yields an Unknown lead (empty partitions,
    # reference core.py:350-355)
    df = tft.frame({"v": np.ones((4, 3))})
    v = tft.block(df, "v")
    assert v.shape == Shape(Unknown, 3)
    r = tft.row(df, "v")
    assert r.shape == Shape(3)


def test_block_missing_column():
    df = tft.frame({"x": np.arange(3.0)})
    with pytest.raises(ValueError, match="Could not find column"):
        tft.block(df, "nope")


# ---------------------------------------------------------------------------
# golden conformance: DSL lowering vs handwritten JAX (ExtractNodes oracle)
# ---------------------------------------------------------------------------

def _jaxpr_prims(fn, *avals):
    return [str(e.primitive) for e in
            jax.make_jaxpr(fn)(*avals).jaxpr.eqns]


@pytest.mark.parametrize("build_dsl,ref_fn", [
    (lambda x: x + 3.0, lambda x: x + 3.0),
    (lambda x: (x * 2.0) / (x + 1.0), lambda x: (x * 2.0) / (x + 1.0)),
    (lambda x: dsl.reduce_sum(x, axis=0),
     lambda x: jnp.sum(x, axis=0).astype(x.dtype)),
    (lambda x: dsl.reduce_min(x, axis=0), lambda x: jnp.min(x, axis=0)),
])
def test_dsl_lowering_matches_jax(build_dsl, ref_fn):
    """The DSL must emit the same primitive sequence as equivalent
    hand-written JAX — the analogue of the reference's node-by-node
    GraphDef comparison against genuine TF (``dsl/ExtractNodes.scala``)."""
    with dsl.with_graph():
        x = dsl.placeholder("double", Shape(4), name="x")
        fetch = build_dsl(x).named("z")
        _, fn = dsl_lower.lower_nodes([fetch])
    aval = jax.ShapeDtypeStruct((4,), np.float64)
    dsl_prims = _jaxpr_prims(lambda a: fn({"x": a})["z"], aval)
    ref_prims = _jaxpr_prims(ref_fn, aval)
    assert dsl_prims == ref_prims


def test_dsl_and_jax_numerical_agreement():
    df = tft.frame({"x": np.linspace(0.0, 1.0, 16)}, num_partitions=2)
    with dsl.with_graph():
        x = tft.block(df, "x")
        z = ((x * 2.0 + 1.0) / 3.0).named("z")
        dsl_out = [r["z"] for r in df.map_blocks(z).collect()]
    jax_out = [r["z"] for r in df.map_blocks(
        lambda x: {"z": (x * 2.0 + 1.0) / 3.0}).collect()]
    np.testing.assert_allclose(dsl_out, jax_out)
