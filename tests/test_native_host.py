"""Second-host-language proof: a C++ program as the executor host.

The reference served a JVM host through javacpp
(``PythonInterface.scala:23-81``); here ``native/host_demo.cpp`` — a
program with no Python and no jax — consumes a computation serialized by
the Python driver and runs it through the C ABI (``tfrpjrt.h``).
"""

import os
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
DEMO = os.path.join(NATIVE, "host_demo")


@pytest.fixture(scope="module")
def demo_bin():
    if not os.path.exists(os.path.join(NATIVE, "libtfrpjrt.so")):
        pytest.skip("libtfrpjrt.so not built")
    r = subprocess.run(["make", "-C", NATIVE, "host_demo"],
                       capture_output=True, text=True, timeout=300)
    # with the core library present, a host_demo build failure is a
    # regression, not an environment gap — fail, don't skip
    assert r.returncode == 0 and os.path.exists(DEMO), r.stderr[-800:]
    return DEMO


def test_jvm_host_runs_python_serialized_computation(tmp_path):
    # the reference's first-class host was a JVM (javacpp JNI,
    # PythonInterface.scala:23-81); native/jni replays host_demo from
    # Java against the same C ABI. Runs only where a JDK exists.
    import shutil

    if shutil.which("javac") is None or shutil.which("java") is None:
        pytest.skip("no JDK in this environment")
    if not os.path.exists(os.path.join(NATIVE, "libtfrpjrt.so")):
        pytest.skip("libtfrpjrt.so not built")
    r = subprocess.run(["make", "-C", NATIVE, "jni"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-800:]

    from tensorframes_tpu import dtypes as _dt
    from tensorframes_tpu.computation import Computation, TensorSpec
    from tensorframes_tpu.shape import Shape, Unknown

    comp = Computation.trace(
        lambda x: {"z": x * 2.0 + 1.0},
        [TensorSpec("x", _dt.double, Shape(Unknown))])
    blob = tmp_path / "comp.tftpu"
    blob.write_bytes(comp.serialize())
    proc = subprocess.run(
        ["java", f"-Dtfr.jni={os.path.join(NATIVE, 'libtfrjni.so')}",
         "-cp", os.path.join(NATIVE, "jni"), "TfrHostDemo",
         str(blob), "8"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-1000:])
    assert "JVM_HOST_OK" in proc.stdout
    assert "first=1.000000 last=15.000000" in proc.stdout


def test_cpp_host_runs_python_serialized_computation(demo_bin, tmp_path):
    from tensorframes_tpu import dtypes as _dt
    from tensorframes_tpu.computation import Computation, TensorSpec
    from tensorframes_tpu.shape import Shape, Unknown

    comp = Computation.trace(
        lambda x: {"z": x * 2.0 + 1.0},
        [TensorSpec("x", _dt.double, Shape(Unknown))])
    blob = tmp_path / "comp.tftpu"
    blob.write_bytes(comp.serialize())

    # the C++ host must refine the symbolic row dim itself (8 rows here,
    # a shape the driver never saw)
    proc = subprocess.run([demo_bin, str(blob), "8"],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "HOST_DEMO_OK" in proc.stdout
    # x = 0..7 -> z = 2x+1: first 1, last 15
    assert "first=1.000000 last=15.000000" in proc.stdout


def test_cpp_host_rejects_garbage(demo_bin, tmp_path):
    bad = tmp_path / "bad.bin"
    bad.write_bytes(b"\x00garbage")
    proc = subprocess.run([demo_bin, str(bad), "4"],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "not a TFTPU1 blob" in proc.stderr
