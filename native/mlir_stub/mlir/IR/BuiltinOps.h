// Minimal stand-in for mlir/IR/BuiltinOps.h: the real LLVM/MLIR headers are
// not shipped in this environment. PJRT headers use mlir::ModuleOp only as a
// by-value parameter of virtual-method overloads this project never calls;
// an opaque single-pointer class keeps declarations (and mangled names)
// identical without the LLVM header tree.
#ifndef MLIR_IR_BUILTINOPS_STUB_H_
#define MLIR_IR_BUILTINOPS_STUB_H_
namespace mlir {
class Operation;
class ModuleOp {
 public:
  ModuleOp() = default;
 private:
  Operation* state_ = nullptr;
};
}  // namespace mlir
#endif
