/* tfruntime — native runtime core for tensorframes_tpu. See tfruntime.h. */

#include "tfruntime.h"

#include <atomic>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

namespace {

std::atomic<int> g_threads{0};  /* 0 = uninitialized -> hardware default */

int hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n ? static_cast<int>(n) : 4;
}

int threads_for(int64_t work_bytes) {
  int t = g_threads.load(std::memory_order_relaxed);
  if (t <= 0) t = hw_threads();
  /* below ~1 MiB the spawn cost dwarfs the win */
  if (work_bytes < (1 << 20)) return 1;
  int64_t max_by_work = work_bytes / (1 << 19);
  if (max_by_work < t) t = static_cast<int>(max_by_work);
  return t < 1 ? 1 : t;
}

/* Run fn(begin, end) over [0, n) split across threads. */
template <typename F>
void parallel_for(int64_t n, int64_t bytes_per_item, F &&fn) {
  int t = threads_for(n * bytes_per_item);
  if (t <= 1 || n < t) {
    fn(static_cast<int64_t>(0), n);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(t - 1);
  int64_t chunk = (n + t - 1) / t;
  for (int i = 1; i < t; ++i) {
    int64_t a = i * chunk, b = a + chunk < n ? a + chunk : n;
    if (a >= b) break;
    pool.emplace_back([&fn, a, b] { fn(a, b); });
  }
  fn(static_cast<int64_t>(0), chunk < n ? chunk : n);
  for (auto &th : pool) th.join();
}

template <typename S, typename D>
void convert_loop(const S *src, D *dst, int64_t a, int64_t b) {
  for (int64_t i = a; i < b; ++i) dst[i] = static_cast<D>(src[i]);
}

template <typename S>
int convert_from(const S *src, void *dst, int dst_dtype, int64_t n) {
  switch (dst_dtype) {
    case TFR_F32:
      parallel_for(n, sizeof(S) + 4, [&](int64_t a, int64_t b) {
        convert_loop(src, static_cast<float *>(dst), a, b);
      });
      return 0;
    case TFR_F64:
      parallel_for(n, sizeof(S) + 8, [&](int64_t a, int64_t b) {
        convert_loop(src, static_cast<double *>(dst), a, b);
      });
      return 0;
    case TFR_I32:
      parallel_for(n, sizeof(S) + 4, [&](int64_t a, int64_t b) {
        convert_loop(src, static_cast<int32_t *>(dst), a, b);
      });
      return 0;
    case TFR_I64:
      parallel_for(n, sizeof(S) + 8, [&](int64_t a, int64_t b) {
        convert_loop(src, static_cast<int64_t *>(dst), a, b);
      });
      return 0;
    case TFR_U8:
      parallel_for(n, sizeof(S) + 1, [&](int64_t a, int64_t b) {
        convert_loop(src, static_cast<uint8_t *>(dst), a, b);
      });
      return 0;
    default:
      return -1;
  }
}

/* ---- buffer pool -------------------------------------------------------- */

constexpr int64_t kAlign = 64;
constexpr int64_t kPoolCap = int64_t(1) << 30; /* keep at most 1 GiB cached */

struct Pool {
  std::mutex mu;
  std::map<int64_t, std::vector<void *>> free_by_size; /* size class -> ptrs */
  int64_t cached_bytes = 0;
};

Pool &pool() {
  static Pool *p = new Pool();
  return *p;
}

int64_t size_class(int64_t nbytes) {
  /* round to next power of two, min 256 bytes, so freelists stay few */
  int64_t c = 256;
  while (c < nbytes) c <<= 1;
  return c;
}

} /* namespace */

extern "C" {

const char *tfr_version(void) { return "tfruntime 0.1.0"; }

void tfr_set_threads(int n) { g_threads.store(n, std::memory_order_relaxed); }

int tfr_get_threads(void) {
  int t = g_threads.load(std::memory_order_relaxed);
  return t > 0 ? t : hw_threads();
}

int tfr_convert(const void *src, int src_dtype, void *dst, int dst_dtype,
                int64_t n) {
  if (n < 0 || !src || !dst) return -1;
  switch (src_dtype) {
    case TFR_F32: return convert_from(static_cast<const float *>(src), dst, dst_dtype, n);
    case TFR_F64: return convert_from(static_cast<const double *>(src), dst, dst_dtype, n);
    case TFR_I32: return convert_from(static_cast<const int32_t *>(src), dst, dst_dtype, n);
    case TFR_I64: return convert_from(static_cast<const int64_t *>(src), dst, dst_dtype, n);
    case TFR_U8:  return convert_from(static_cast<const uint8_t *>(src), dst, dst_dtype, n);
    default: return -1;
  }
}

int tfr_gather_rows(const void *src, int64_t n_src, const int64_t *idx,
                    int64_t n_idx, int64_t row_bytes, void *dst) {
  if (!src || !idx || !dst || row_bytes <= 0 || n_idx < 0) return -1;
  const char *s = static_cast<const char *>(src);
  char *d = static_cast<char *>(dst);
  std::atomic<int> bad{0};
  parallel_for(n_idx, row_bytes, [&](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      int64_t j = idx[i];
      if (j < 0 || j >= n_src) {
        bad.store(1, std::memory_order_relaxed);
        return;
      }
      std::memcpy(d + i * row_bytes, s + j * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  });
  return bad.load() ? -1 : 0;
}

int64_t tfr_pack_ragged(const void *const *ptrs, const int64_t *nbytes,
                        int64_t n, void *dst, int64_t *offsets) {
  if (!nbytes || n < 0) return -1;
  std::vector<int64_t> offs(static_cast<size_t>(n) + 1);
  for (int64_t i = 0; i < n; ++i) offs[i + 1] = offs[i] + nbytes[i];
  int64_t total = offs[static_cast<size_t>(n)];
  if (offsets) std::memcpy(offsets, offs.data(), (n + 1) * sizeof(int64_t));
  if (dst && ptrs) {
    /* offsets are precomputed, so row copies are independent */
    char *d = static_cast<char *>(dst);
    int64_t avg = n ? total / n : 0;
    parallel_for(n, avg ? avg : 1, [&](int64_t a, int64_t b) {
      for (int64_t i = a; i < b; ++i)
        std::memcpy(d + offs[static_cast<size_t>(i)], ptrs[i],
                    static_cast<size_t>(nbytes[i]));
    });
  }
  return total;
}

int tfr_pad_ragged(const void *const *ptrs, const int64_t *lens, int64_t n,
                   int64_t max_len, int64_t es, void *dst, uint8_t *mask) {
  if (!ptrs || !lens || !dst || n < 0 || max_len < 0 || es <= 0) return -1;
  for (int64_t i = 0; i < n; ++i)
    if (lens[i] > max_len || lens[i] < 0) return -1;
  char *d = static_cast<char *>(dst);
  parallel_for(n, max_len * es, [&](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      int64_t len = lens[i];
      char *row = d + i * max_len * es;
      std::memcpy(row, ptrs[i], static_cast<size_t>(len * es));
      std::memset(row + len * es, 0, static_cast<size_t>((max_len - len) * es));
      if (mask) {
        uint8_t *mrow = mask + i * max_len;
        std::memset(mrow, 1, static_cast<size_t>(len));
        std::memset(mrow + len, 0, static_cast<size_t>(max_len - len));
      }
    }
  });
  return 0;
}

void *tfr_alloc(int64_t nbytes) {
  if (nbytes <= 0) nbytes = 1;
  int64_t cls = size_class(nbytes);
  Pool &p = pool();
  {
    std::lock_guard<std::mutex> lk(p.mu);
    auto it = p.free_by_size.find(cls);
    if (it != p.free_by_size.end() && !it->second.empty()) {
      void *ptr = it->second.back();
      it->second.pop_back();
      p.cached_bytes -= cls;
      return ptr;
    }
  }
  return ::operator new(static_cast<size_t>(cls),
                        std::align_val_t(kAlign), std::nothrow);
}

void tfr_free(void *ptr, int64_t nbytes) {
  if (!ptr) return;
  int64_t cls = size_class(nbytes <= 0 ? 1 : nbytes);
  Pool &p = pool();
  {
    std::lock_guard<std::mutex> lk(p.mu);
    if (p.cached_bytes + cls <= kPoolCap) {
      p.free_by_size[cls].push_back(ptr);
      p.cached_bytes += cls;
      return;
    }
  }
  ::operator delete(ptr, std::align_val_t(kAlign));
}

int64_t tfr_pool_bytes(void) {
  Pool &p = pool();
  std::lock_guard<std::mutex> lk(p.mu);
  return p.cached_bytes;
}

void tfr_pool_trim(void) {
  Pool &p = pool();
  std::lock_guard<std::mutex> lk(p.mu);
  for (auto &kv : p.free_by_size)
    for (void *ptr : kv.second)
      ::operator delete(ptr, std::align_val_t(kAlign));
  p.free_by_size.clear();
  p.cached_bytes = 0;
}

} /* extern "C" */
