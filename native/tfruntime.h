/* tfruntime — native runtime core for tensorframes_tpu.
 *
 * The reference framework's execution path bottoms out in a C++ runtime
 * (libtensorflow via javacpp JNI; see SURVEY.md §2.2). In the TPU-native
 * design, XLA is the compute engine, and THIS library is the native side of
 * everything around it: the host-side marshalling hot loops
 * (DataOps.convert / convertBack analogues), ragged-cell packing, and an
 * aligned, pooled host allocator for staging buffers.
 *
 * Pure C ABI — consumed from Python via ctypes (tensorframes_tpu/native.py)
 * with a numpy fallback when the library is not built.
 */
#ifndef TFRUNTIME_H
#define TFRUNTIME_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* dtype codes (stable ABI; mirrored in tensorframes_tpu/native.py) */
enum tfr_dtype {
  TFR_F32 = 0,
  TFR_F64 = 1,
  TFR_I32 = 2,
  TFR_I64 = 3,
  TFR_U8  = 4,
};

const char *tfr_version(void);

/* Parallelism knob for the conversion/gather kernels. n <= 0 resets to the
 * hardware default. */
void tfr_set_threads(int n);
int  tfr_get_threads(void);

/* Elementwise dtype conversion src[0..n) -> dst[0..n), multithreaded for
 * large n. Returns 0 on success, -1 on unsupported dtype pair. */
int tfr_convert(const void *src, int src_dtype, void *dst, int dst_dtype,
                int64_t n);

/* Row gather: dst[i] = src[idx[i]] where each row is row_bytes wide.
 * idx values must be in [0, n_src). Returns 0, or -1 on a bad index. */
int tfr_gather_rows(const void *src, int64_t n_src, const int64_t *idx,
                    int64_t n_idx, int64_t row_bytes, void *dst);

/* Ragged pack: concatenate n buffers (ptrs[i], nbytes[i]) into dst;
 * offsets[0..n] gets the CSR byte offsets (offsets[n] = total). dst may be
 * NULL to only compute offsets. Returns total bytes. */
int64_t tfr_pack_ragged(const void *const *ptrs, const int64_t *nbytes,
                        int64_t n, void *dst, int64_t *offsets);

/* Ragged pad-to-dense: row i holds lens[i] elements of elem_size bytes;
 * dst is [n, max_len] elements, zero padded; mask (may be NULL) is
 * [n, max_len] bytes, 1 = valid. Returns 0, or -1 if some lens[i] > max_len. */
int tfr_pad_ragged(const void *const *ptrs, const int64_t *lens, int64_t n,
                   int64_t max_len, int64_t elem_size, void *dst,
                   uint8_t *mask);

/* Pooled 64-byte-aligned host allocation. Freed buffers are kept in
 * per-size-class freelists for reuse (staging buffers have a few hot
 * sizes); tfr_pool_trim releases them to the OS. */
void   *tfr_alloc(int64_t nbytes);
void    tfr_free(void *p, int64_t nbytes);
int64_t tfr_pool_bytes(void);   /* bytes currently cached in freelists */
void    tfr_pool_trim(void);

#ifdef __cplusplus
}
#endif

#endif /* TFRUNTIME_H */
