/* tfrpjrt: C interface of the native PJRT execution core.
 *
 * The TPU-native analogue of the reference's libtensorflow C++ session
 * layer (TensorFlowOps.scala:46-64 readGraph/withSession + session.Run):
 * a serialized StableHLO computation is loaded, compiled and executed
 * entirely in C++, with host buffers exposed to the caller for zero-copy
 * reads (results are written straight into caller-provided memory).
 *
 * Two backends behind one interface:
 *   - "cpu" / "cpu:<n>"  — XLA:CPU hosted in-process via the PJRT C++ API
 *     (linked from libtensorflow_cc; the local-test backend);
 *   - "plugin:<path>"    — any PJRT C API plugin loaded with dlopen;
 *     on TPU hosts, libtpu.so (the production backend).
 *
 * All functions are thread-compatible; a client may be shared across
 * threads (PJRT clients are thread-safe; no tfLock analogue is needed,
 * unlike the reference's global lock, DebugRowOps.scala:718-719).
 */
#ifndef TFRPJRT_H_
#define TFRPJRT_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tfr_pjrt_client tfr_pjrt_client;
typedef struct tfr_pjrt_exe tfr_pjrt_exe;
typedef struct tfr_pjrt_results tfr_pjrt_results;
/* A device-resident buffer detached from a results set: lets a caller
 * chain executions without a host round-trip per dispatch (the
 * device-resident loop the jax path gets for free). */
typedef struct tfr_pjrt_buffer tfr_pjrt_buffer;

/* dtype codes (stable across backends; mapped internally) */
enum tfr_dtype {
  TFR_F32 = 1,
  TFR_F64 = 2,
  TFR_I32 = 3,
  TFR_I64 = 4,
  TFR_BF16 = 5,
  TFR_PRED = 6,
};

/* Create a client. spec: "cpu", "cpu:<ndevices>", or "plugin:<path.so>".
 * Either form may carry URL-style options: "plugin:<path>?k=v&k2=v2".
 * Values that parse as integers are passed to the plugin as int64
 * NamedValues, everything else as strings (PJRT_Client_Create
 * create_options — how proxied plugins such as axon receive their
 * topology/session configuration). The reserved key "tfr_device"
 * selects the addressable-device ordinal this client executes on
 * (default 0) and is not forwarded to the plugin.
 * Returns NULL on failure with a message in err. */
tfr_pjrt_client* tfr_pjrt_client_create(const char* spec, char* err,
                                        int errlen);
void tfr_pjrt_client_destroy(tfr_pjrt_client* c);
int tfr_pjrt_client_device_count(tfr_pjrt_client* c);
/* Writes the platform name into out; returns its length. */
int tfr_pjrt_client_platform(tfr_pjrt_client* c, char* out, int outlen);

/* Compile a StableHLO module (text or MLIR bytecode). */
tfr_pjrt_exe* tfr_pjrt_compile(tfr_pjrt_client* c, const char* module_bytes,
                               long module_len, char* err, int errlen);

/* Compile a DYNAMIC-shape serialized StableHLO module (the jax.export
 * wire format with symbolic dims) at the given concrete argument shapes:
 * shape refinement + lowering to HLO happen natively, so the executing
 * host needs no jax. cc_version is the module's calling-convention
 * version; platforms_csv lists the platforms it was lowered for (comma
 * separated, in order) and select_platform picks this host's entry when
 * there is more than one. dtypes/ndims/dims describe the argument shapes
 * exactly as in tfr_pjrt_execute. */
tfr_pjrt_exe* tfr_pjrt_compile_dynamic(
    tfr_pjrt_client* c, const char* module_bytes, long module_len,
    int cc_version, const char* platforms_csv, const char* select_platform,
    int nargs, const int* dtypes, const int* ndims, const long long* dims,
    char* err, int errlen);

/* As tfr_pjrt_compile_dynamic, replicated n_replicas times (SPMD). */
tfr_pjrt_exe* tfr_pjrt_compile_dynamic_n(
    tfr_pjrt_client* c, const char* module_bytes, long module_len,
    int cc_version, const char* platforms_csv, const char* select_platform,
    int nargs, const int* dtypes, const int* ndims, const long long* dims,
    int n_replicas, char* err, int errlen);

/* SPMD-replicated compile: one program instance per device,
 * n_replicas <= device count (and < 128). */
tfr_pjrt_exe* tfr_pjrt_compile_n(tfr_pjrt_client* c,
                                 const char* module_bytes, long module_len,
                                 int n_replicas, char* err, int errlen);

/* GSPMD-partitioned compile: num_replicas = 1, num_partitions =
 * n_partitions, SPMD partitioning ON. The module is a jax mesh lowering
 * (GSPMD flavor): GLOBAL-shaped parameters/results annotated with
 * mhlo.sharding attributes; XLA's SPMD partitioner splits it into the
 * per-device program, inserting the ICI/host collectives the shardings
 * imply. Execute with tfr_pjrt_execute_replicated, n = n_partitions; each
 * device's argument is its SHARD of the global array (dims describe the
 * shard — all shards equal-shaped, row-axis padding is the caller's job),
 * and results come back device-major as shards (replicated outputs: one
 * full copy per device). */
tfr_pjrt_exe* tfr_pjrt_compile_spmd(tfr_pjrt_client* c,
                                    const char* module_bytes,
                                    long module_len, int n_partitions,
                                    char* err, int errlen);

/* Execute a replicated executable across its devices in ONE call.
 * data holds n_replicas * nargs host pointers, replica-major; every
 * replica shares the same shapes (dtypes/ndims/dims as in
 * tfr_pjrt_execute). Results are replica-major: n_replicas * n_outputs
 * entries. */
tfr_pjrt_results* tfr_pjrt_execute_replicated(
    tfr_pjrt_client* c, tfr_pjrt_exe* e, int n_replicas, int nargs,
    const int* dtypes, const int* ndims, const long long* dims,
    const void* const* data, char* err, int errlen);

void tfr_pjrt_exe_destroy(tfr_pjrt_exe* e);

/* Execute on the client's device (ordinal "tfr_device" from the spec;
 * default 0). Inputs are dense row-major host buffers.
 * dims is one flat array; ndims[i] gives each argument's rank and the
 * dims of argument i follow those of i-1. Returns NULL on failure. */
tfr_pjrt_results* tfr_pjrt_execute(tfr_pjrt_client* c, tfr_pjrt_exe* e,
                                   int nargs, const int* dtypes,
                                   const int* ndims, const long long* dims,
                                   const void* const* data, char* err,
                                   int errlen);

int tfr_pjrt_results_count(tfr_pjrt_results* r);
/* dims must have room for 8 entries; returns 0 on success. */
int tfr_pjrt_result_meta(tfr_pjrt_results* r, int i, int* dtype, int* ndim,
                         long long* dims);
/* Copy result i (dense row-major) into dst; nbytes must match exactly.
 * Returns 0 on success. */
int tfr_pjrt_result_read(tfr_pjrt_results* r, int i, void* dst,
                         long long nbytes, char* err, int errlen);
void tfr_pjrt_results_destroy(tfr_pjrt_results* r);

/* Detach result i as a standalone DEVICE-RESIDENT buffer handle. The
 * buffer stays in device memory (HBM on TPU); the results slot is
 * emptied (meta/read on it fail afterwards). The caller owns the handle
 * and may pass it back as an input to
 * tfr_pjrt_execute_replicated_mixed — the residency contract that turns
 * per-call host marshalling into a device loop. Returns NULL on
 * out-of-range or already-released slots. */
tfr_pjrt_buffer* tfr_pjrt_result_release_buffer(tfr_pjrt_results* r, int i);
/* dims must have room for 8 entries; returns 0 on success. */
int tfr_pjrt_buffer_meta(tfr_pjrt_buffer* b, int* dtype, int* ndim,
                         long long* dims);
void tfr_pjrt_buffer_destroy(tfr_pjrt_buffer* b);

/* As tfr_pjrt_execute_replicated, but each (replica, arg) slot may be a
 * device-resident buffer instead of host memory: dev_bufs holds
 * n_replicas * nargs entries, replica-major; a non-NULL entry is used
 * directly (it must live on that replica's device — true for buffers
 * released from a result slot of the same (replica, executable-family)
 * position) and the corresponding data entry is ignored. dev_bufs NULL
 * means all-host (identical to tfr_pjrt_execute_replicated). dtypes/
 * ndims/dims still describe every argument (device entries included —
 * they are part of the program signature). Buffers are NOT consumed:
 * the same handle may be passed to many executions and must still be
 * destroyed by the caller. */
tfr_pjrt_results* tfr_pjrt_execute_replicated_mixed(
    tfr_pjrt_client* c, tfr_pjrt_exe* e, int n_replicas, int nargs,
    const int* dtypes, const int* ndims, const long long* dims,
    const void* const* data, tfr_pjrt_buffer* const* dev_bufs, char* err,
    int errlen);

#ifdef __cplusplus
}
#endif

#endif /* TFRPJRT_H_ */
