// Native PJRT execution core (see tfrpjrt.h for the interface contract).
//
// The reference executes every graph in C++ through libtensorflow sessions
// (TensorFlowOps.scala:46-64, DebugRowOps.scala:776-788); this is the
// TPU-native equivalent: serialized StableHLO in, XLA compile + execute in
// C++, results written straight into caller-owned host memory.
//
//   backend "cpu"           — XLA:CPU via the PJRT C++ API, linked from
//                             libtensorflow_cc (local tests; same compiler
//                             stack XLA uses everywhere);
//   backend "plugin:<path>" — any PJRT C API plugin via dlopen, e.g.
//                             /...//libtpu.so on TPU hosts. Pure C ABI.
//
// LLVM/MLIR headers are not shipped in this environment, so mlir-typed
// PJRT entry points are declared through a one-pointer stub (mlir_stub/)
// and the module parse goes through the exported
// ParseMlirModuleStringAndConvertToXlaComputation symbol instead of
// mlir_to_hlo.h. NDEBUG is required: tsl AsyncValue type-ids are assigned
// per-DSO, so its DCHECK-only accessor checks cannot pass across the
// library boundary (the data accesses themselves are layout-stable).

#include "tfrpjrt.h"

#include <dlfcn.h>

#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <string_view>
#include <vector>

#include <mutex>

#include "xla/pjrt/pjrt_client.h"
#include "xla/pjrt/pjrt_executable.h"
#include "xla/pjrt/plugin/xla_cpu/xla_cpu_pjrt_client.h"
#include "xla/hlo/builder/xla_computation.h"
#include "xla/pjrt/c/pjrt_c_api.h"
#include "xla/shape.h"
#include "xla/shape_util.h"
#include "xla/service/hlo.pb.h"

namespace xla {
// Declared here to avoid mlir_to_hlo.h's LLVM header dependency; resolved
// against the exported symbol in libtensorflow_cc.
absl::Status ParseMlirModuleStringAndConvertToXlaComputation(
    std::string_view mlir_module_str, XlaComputation& xla_computation,
    bool use_tuple_args, bool return_tuple);
}  // namespace xla

// ---------------------------------------------------------------------------
// ABI declarations for tensorflow::XlaCallModuleLoader (the jax.export /
// XlaCallModule dynamic-shape loader in libtensorflow_cc) without the
// LLVM/MLIR headers this environment does not ship. Only layout-stable
// value types cross the boundary: llvm::StringRef and llvm::ArrayRef are
// {pointer, size} pairs; mlir::MLIRContext is a single-unique_ptr pimpl
// constructed through its exported out-of-line constructor.
// ---------------------------------------------------------------------------

namespace mlir {
class MLIRContext {
 public:
  enum class Threading { DISABLED, ENABLED };
  explicit MLIRContext(Threading t);
  ~MLIRContext();

 private:
  void* impl_;  // stands in for std::unique_ptr<MLIRContextImpl>
};
}  // namespace mlir

namespace llvm {
class StringRef {
 public:
  StringRef(const char* d, size_t l) : data_(d), len_(l) {}
  const char* data_;
  size_t len_;
};
template <typename T>
class ArrayRef {
 public:
  ArrayRef(const T* d, size_t l) : data_(d), len_(l) {}
  const T* data_;
  size_t len_;
};
}  // namespace llvm

namespace tensorflow {
class XlaCallModuleLoader {
 public:
  static absl::StatusOr<std::unique_ptr<XlaCallModuleLoader>> Create(
      mlir::MLIRContext* context, int version, llvm::StringRef module_str,
      std::vector<std::string> disabled_checks,
      std::vector<std::string> platforms, int num_invocation_args,
      bool main_has_token_input_output, bool use_shardy_partitioner);
  absl::Status SetPlatformIndex(std::string_view compilation_platform);
  absl::Status RefineDynamicShapes(llvm::ArrayRef<xla::Shape> input_shapes);
  absl::Status ValidateStaticShapes();
  absl::StatusOr<xla::XlaComputation> ToXlaComputation();
};
}  // namespace tensorflow

namespace {

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    std::snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

// "plugin:/x.so?topology=v5e:1x1x1&n_slices=1" -> base "plugin:/x.so" +
// ordered (key, value) pairs. Only the LAST '?' before the first '&'
// region is honored as the option separator so .so paths containing '?'
// (never in practice) don't need escaping.
struct SpecOption {
  std::string key;
  std::string value;
  bool is_int = false;
  long long int_value = 0;
};

std::vector<SpecOption> parse_spec_options(std::string* spec) {
  std::vector<SpecOption> out;
  auto q = spec->find('?');
  if (q == std::string::npos) return out;
  std::string opts = spec->substr(q + 1);
  spec->resize(q);
  size_t pos = 0;
  while (pos <= opts.size()) {
    auto amp = opts.find('&', pos);
    std::string pair = opts.substr(
        pos, amp == std::string::npos ? std::string::npos : amp - pos);
    if (!pair.empty()) {
      SpecOption o;
      auto eq = pair.find('=');
      if (eq == std::string::npos) {
        o.key = pair;
      } else {
        o.key = pair.substr(0, eq);
        o.value = pair.substr(eq + 1);
      }
      if (!o.value.empty()) {
        char* end = nullptr;
        errno = 0;
        long long v = std::strtoll(o.value.c_str(), &end, 10);
        if (errno == 0 && end && *end == '\0') {
          o.is_int = true;
          o.int_value = v;
        }
      }
      out.push_back(std::move(o));
    }
    if (amp == std::string::npos) break;
    pos = amp + 1;
  }
  return out;
}

// Pops the reserved "tfr_device" option; returns the ordinal (default 0).
int take_device_ordinal(std::vector<SpecOption>* opts) {
  int ordinal = 0;
  for (auto it = opts->begin(); it != opts->end();) {
    if (it->key == "tfr_device") {
      if (it->is_int) ordinal = static_cast<int>(it->int_value);
      it = opts->erase(it);
    } else {
      ++it;
    }
  }
  return ordinal;
}

// ---------------------------------------------------------------------------
// Backend interface
// ---------------------------------------------------------------------------

// A device-resident buffer detached from a results set; passed back as
// an execution input to keep loop state in device memory across
// dispatches (no host round-trip per call).
struct BufIface {
  virtual ~BufIface() = default;
  virtual int meta(int* dtype, int* ndim, long long* dims) const = 0;
};

struct ResultsIface {
  virtual ~ResultsIface() = default;
  virtual int count() const = 0;
  virtual int meta(int i, int* dtype, int* ndim, long long* dims) const = 0;
  virtual int read(int i, void* dst, long long nbytes, std::string* err) = 0;
  // Detach slot i as a standalone device buffer (slot becomes empty);
  // nullptr on out-of-range / already-released slots.
  virtual BufIface* release(int i) = 0;
};

struct ExeIface {
  virtual ~ExeIface() = default;
};

struct ClientIface {
  virtual ~ClientIface() = default;
  virtual int device_count() const = 0;
  virtual std::string platform() const = 0;
  virtual ExeIface* compile(std::string_view module, std::string* err) = 0;
  // Compile a serialized xla.HloModuleProto (the output of the dynamic-
  // shape refinement below), replicated n_replicas times (1 = single).
  virtual ExeIface* compile_hlo(const std::string& hlo_proto,
                                std::string* err, int n_replicas = 1) = 0;
  virtual ResultsIface* execute(ExeIface* exe, int nargs, const int* dtypes,
                                const int* ndims, const long long* dims,
                                const void* const* data,
                                std::string* err) = 0;
  // SPMD-replicated: compile for n_replicas devices and run one program
  // instance per device in a single call (the per-executor parallel
  // dispatch of the reference's executor fleet, in-process).
  virtual ExeIface* compile_n(std::string_view module, int n_replicas,
                              std::string* err) = 0;
  // GSPMD-partitioned: ONE logical program over n_partitions devices
  // (num_replicas=1, use_spmd_partitioning on); the module carries
  // mhlo.sharding annotations from a jax mesh lowering and XLA's SPMD
  // partitioner emits the per-device program + collectives. This is the
  // mesh layer's executor: the distributed half of the framework running
  // in C++, not just the per-partition half.
  virtual ExeIface* compile_spmd(std::string_view module, int n_partitions,
                                 std::string* err) = 0;
  // data: n_replicas * nargs host pointers, replica-major; every replica
  // shares the same shapes. Results are replica-major too
  // (n_replicas * n_outputs entries).
  virtual ResultsIface* execute_replicated(
      ExeIface* exe, int n_replicas, int nargs, const int* dtypes,
      const int* ndims, const long long* dims, const void* const* data,
      std::string* err) {
    return execute_replicated_mixed(exe, n_replicas, nargs, dtypes, ndims,
                                    dims, data, nullptr, err);
  }
  // As execute_replicated, but a non-null dev_bufs[r*nargs + a] entry is
  // used as that slot's input directly (device-resident, not consumed —
  // the caller still owns it); the matching data entry is ignored.
  virtual ResultsIface* execute_replicated_mixed(
      ExeIface* exe, int n_replicas, int nargs, const int* dtypes,
      const int* ndims, const long long* dims, const void* const* data,
      BufIface* const* dev_bufs, std::string* err) = 0;
};

long long dense_elems(int ndim, const long long* dims) {
  long long n = 1;
  for (int i = 0; i < ndim; ++i) n *= dims[i];
  return n;
}

int dtype_size(int dt) {
  switch (dt) {
    case TFR_F32: case TFR_I32: return 4;
    case TFR_F64: case TFR_I64: return 8;
    case TFR_BF16: return 2;
    case TFR_PRED: return 1;
  }
  return 0;
}

xla::PrimitiveType to_xla_type(int dt);  // defined below

// Refine a serialized jax.export StableHLO module (symbolic/dynamic dims)
// at concrete argument shapes and lower it to a serialized HloModuleProto —
// entirely in C++, no jax on the executing host. This is the executor-side
// step the reference performed by parsing GraphDef bytes in libtensorflow
// (TensorFlowOps.scala:46-52); here the shipped program is StableHLO and
// the shape specialization runs TF's XlaCallModuleLoader refinement.
absl::StatusOr<std::string> refine_to_hlo_proto(
    std::string_view module_bytes, int cc_version,
    const std::vector<std::string>& platforms,
    const std::string& select_platform, int nargs, const int* dtypes,
    const int* ndims, const long long* dims) {
  // one context + one refinement at a time: the loader mutates the module
  // and MLIR contexts are not cheap; serialize access behind a mutex
  static std::mutex mu;
  static mlir::MLIRContext* ctx = new mlir::MLIRContext(
      mlir::MLIRContext::Threading::DISABLED);
  std::lock_guard<std::mutex> lock(mu);

  auto loader_or = tensorflow::XlaCallModuleLoader::Create(
      ctx, cc_version,
      llvm::StringRef(module_bytes.data(), module_bytes.size()),
      /*disabled_checks=*/{}, platforms, /*num_invocation_args=*/nargs,
      /*main_has_token_input_output=*/false,
      /*use_shardy_partitioner=*/false);
  if (!loader_or.ok()) return loader_or.status();
  // Intentionally released, never deleted: the stub declaration above has
  // no destructor knowledge, and callers cache the compiled executable per
  // signature, so the leak is one module-sized object per native compile.
  tensorflow::XlaCallModuleLoader* loader = loader_or.value().release();
  if (platforms.size() > 1) {
    auto st = loader->SetPlatformIndex(select_platform);
    if (!st.ok()) return st;
  }
  std::vector<xla::Shape> shapes;
  const long long* d = dims;
  for (int a = 0; a < nargs; ++a) {
    std::vector<int64_t> shp(d, d + ndims[a]);
    d += ndims[a];
    shapes.push_back(xla::ShapeUtil::MakeShape(
        to_xla_type(dtypes[a]),
        absl::Span<const int64_t>(shp.data(), shp.size())));
  }
  auto st = loader->RefineDynamicShapes(
      llvm::ArrayRef<xla::Shape>(shapes.data(), shapes.size()));
  if (!st.ok()) return st;
  st = loader->ValidateStaticShapes();
  if (!st.ok()) return st;
  auto xc_or = loader->ToXlaComputation();
  if (!xc_or.ok()) return xc_or.status();
  return xc_or.value().proto().SerializeAsString();
}

// ---------------------------------------------------------------------------
// C++-API backend (XLA:CPU from libtensorflow_cc)
// ---------------------------------------------------------------------------

xla::PrimitiveType to_xla_type(int dt) {
  switch (dt) {
    case TFR_F32: return xla::PrimitiveType::F32;
    case TFR_F64: return xla::PrimitiveType::F64;
    case TFR_I32: return xla::PrimitiveType::S32;
    case TFR_I64: return xla::PrimitiveType::S64;
    case TFR_BF16: return xla::PrimitiveType::BF16;
    case TFR_PRED: return xla::PrimitiveType::PRED;
  }
  return xla::PrimitiveType::PRIMITIVE_TYPE_INVALID;
}

int from_xla_type(xla::PrimitiveType t) {
  switch (t) {
    case xla::PrimitiveType::F32: return TFR_F32;
    case xla::PrimitiveType::F64: return TFR_F64;
    case xla::PrimitiveType::S32: return TFR_I32;
    case xla::PrimitiveType::S64: return TFR_I64;
    case xla::PrimitiveType::BF16: return TFR_BF16;
    case xla::PrimitiveType::PRED: return TFR_PRED;
    default: return 0;
  }
}

struct CppExe : ExeIface {
  std::unique_ptr<xla::PjRtLoadedExecutable> exe;
};

struct CppBuf : BufIface {
  std::unique_ptr<xla::PjRtBuffer> buf;

  int meta(int* dtype, int* ndim, long long* dims) const override {
    *dtype = from_xla_type(buf->element_type());
    auto d = buf->dimensions();
    if (d.size() > 8) return 2;
    *ndim = static_cast<int>(d.size());
    for (size_t k = 0; k < d.size(); ++k) dims[k] = d[k];
    return 0;
  }
};

struct CppResults : ResultsIface {
  std::vector<std::unique_ptr<xla::PjRtBuffer>> bufs;

  int count() const override { return static_cast<int>(bufs.size()); }

  BufIface* release(int i) override {
    if (i < 0 || i >= count() || !bufs[i]) return nullptr;
    auto* b = new CppBuf();
    b->buf = std::move(bufs[i]);  // slot left empty; meta/read now fail
    return b;
  }

  int meta(int i, int* dtype, int* ndim, long long* dims) const override {
    if (i < 0 || i >= count() || !bufs[i]) return 1;
    const auto& b = bufs[i];
    *dtype = from_xla_type(b->element_type());
    auto d = b->dimensions();
    if (d.size() > 8) return 2;
    *ndim = static_cast<int>(d.size());
    for (size_t k = 0; k < d.size(); ++k) dims[k] = d[k];
    return 0;
  }

  int read(int i, void* dst, long long nbytes, std::string* err) override {
    if (i < 0 || i >= count() || !bufs[i]) {
      *err = "result index out of range or buffer released";
      return 1;
    }
    auto& b = bufs[i];
    auto sz = b->GetOnDeviceSizeInBytes();
    if (!sz.ok()) { *err = sz.status().ToString(); return 1; }
    if (static_cast<long long>(*sz) != nbytes) {
      *err = "size mismatch: device has " + std::to_string(*sz) +
             " bytes, caller expects " + std::to_string(nbytes) +
             " (non-dense layout?)";
      return 1;
    }
    auto st = b->CopyRawToHost(dst, 0, *sz).Await();
    if (!st.ok()) { *err = st.ToString(); return 1; }
    return 0;
  }
};

struct CppClient : ClientIface {
  std::unique_ptr<xla::PjRtClient> client;
  int device_ordinal = 0;

  int device_count() const override { return client->device_count(); }

  std::string platform() const override {
    return std::string(client->platform_name());
  }

  ExeIface* compile(std::string_view module, std::string* err) override {
    xla::XlaComputation xc;
    auto st = xla::ParseMlirModuleStringAndConvertToXlaComputation(
        module, xc, /*use_tuple_args=*/false, /*return_tuple=*/false);
    if (!st.ok()) { *err = st.ToString(); return nullptr; }
    return compile_xla(std::move(xc), err);
  }

  ExeIface* compile_hlo(const std::string& hlo_proto, std::string* err,
                        int n_replicas = 1) override {
    xla::HloModuleProto proto;
    if (!proto.ParseFromString(hlo_proto)) {
      *err = "HloModuleProto parse failed";
      return nullptr;
    }
    return compile_xla(xla::XlaComputation(std::move(proto)), err,
                       n_replicas);
  }

  ExeIface* compile_xla(xla::XlaComputation xc, std::string* err,
                        int n_replicas = 1, int n_partitions = 1) {
    xla::CompileOptions opts;
    if (n_replicas > 1) {
      opts.executable_build_options.set_num_replicas(n_replicas);
    }
    if (n_partitions > 1) {
      opts.executable_build_options.set_num_partitions(n_partitions);
      opts.executable_build_options.set_use_spmd_partitioning(true);
    }
    auto exe_or = client->CompileAndLoad(xc, opts);
    if (!exe_or.ok()) { *err = exe_or.status().ToString(); return nullptr; }
    auto* e = new CppExe();
    e->exe = std::move(exe_or).value();
    return e;
  }

  ExeIface* compile_n(std::string_view module, int n_replicas,
                      std::string* err) override {
    if (n_replicas < 1 || n_replicas > device_count()) {
      *err = "n_replicas " + std::to_string(n_replicas) +
             " out of range (1.." + std::to_string(device_count()) + ")";
      return nullptr;
    }
    xla::XlaComputation xc;
    auto st = xla::ParseMlirModuleStringAndConvertToXlaComputation(
        module, xc, /*use_tuple_args=*/false, /*return_tuple=*/false);
    if (!st.ok()) { *err = st.ToString(); return nullptr; }
    return compile_xla(std::move(xc), err, n_replicas);
  }

  ExeIface* compile_spmd(std::string_view module, int n_partitions,
                         std::string* err) override {
    if (n_partitions < 1 || n_partitions > device_count()) {
      *err = "n_partitions " + std::to_string(n_partitions) +
             " out of range (1.." + std::to_string(device_count()) + ")";
      return nullptr;
    }
    xla::XlaComputation xc;
    auto st = xla::ParseMlirModuleStringAndConvertToXlaComputation(
        module, xc, /*use_tuple_args=*/false, /*return_tuple=*/false);
    if (!st.ok()) { *err = st.ToString(); return nullptr; }
    return compile_xla(std::move(xc), err, /*n_replicas=*/1, n_partitions);
  }

  ResultsIface* execute_replicated_mixed(ExeIface* exe_i, int n_replicas,
                                         int nargs, const int* dtypes,
                                         const int* ndims,
                                         const long long* dims,
                                         const void* const* data,
                                         BufIface* const* dev_bufs,
                                         std::string* err) override {
    auto* exe = static_cast<CppExe*>(exe_i);
    // the executable's own devices, in execution order — covers both
    // replicated (n replicas x 1 partition) and GSPMD-partitioned
    // (1 replica x n partitions) executables; Execute's argument lists
    // are positional over this same sequence
    auto exe_devices = exe->exe->addressable_devices();
    if (n_replicas < 1 ||
        n_replicas != static_cast<int>(exe_devices.size())) {
      *err = "n devices " + std::to_string(n_replicas) +
             " does not match the executable's device count " +
             std::to_string(exe_devices.size());
      return nullptr;
    }
    std::vector<std::vector<std::unique_ptr<xla::PjRtBuffer>>> in_bufs(
        n_replicas);
    std::vector<std::vector<xla::PjRtBuffer*>> arg_lists(n_replicas);
    for (int r = 0; r < n_replicas; ++r) {
      xla::PjRtDevice* device = exe_devices[r];
      auto ms_or = device->default_memory_space();
      if (!ms_or.ok()) { *err = ms_or.status().ToString(); return nullptr; }
      const long long* d = dims;
      for (int a = 0; a < nargs; ++a) {
        std::vector<int64_t> shape(d, d + ndims[a]);
        d += ndims[a];
        if (dev_bufs && dev_bufs[r * nargs + a]) {
          // device-resident input: borrowed, not consumed (the caller
          // keeps ownership; default-compiled programs donate nothing)
          arg_lists[r].push_back(
              static_cast<CppBuf*>(dev_bufs[r * nargs + a])->buf.get());
          continue;
        }
        auto buf_or = client->BufferFromHostBuffer(
            data[r * nargs + a], to_xla_type(dtypes[a]), shape,
            std::nullopt,
            xla::PjRtClient::HostBufferSemantics::kImmutableOnlyDuringCall,
            nullptr, ms_or.value(), nullptr);
        if (!buf_or.ok()) {
          *err = buf_or.status().ToString();
          return nullptr;
        }
        in_bufs[r].push_back(std::move(buf_or).value());
        arg_lists[r].push_back(in_bufs[r].back().get());
      }
    }
    // multi-output programs come back as one tuple buffer unless asked
    // to untuple; CppResults expects one buffer per output
    xla::ExecuteOptions exec_opts;
    exec_opts.untuple_result = true;
    auto out_or = exe->exe->Execute(absl::MakeSpan(arg_lists), exec_opts);
    if (!out_or.ok()) { *err = out_or.status().ToString(); return nullptr; }
    auto* res = new CppResults();
    for (auto& per_replica : out_or.value()) {
      for (auto& b : per_replica) res->bufs.push_back(std::move(b));
    }
    return res;
  }

  ResultsIface* execute(ExeIface* exe_i, int nargs, const int* dtypes,
                        const int* ndims, const long long* dims,
                        const void* const* data, std::string* err) override {
    auto* exe = static_cast<CppExe*>(exe_i);
    auto devices = client->addressable_devices();
    if (device_ordinal < 0 ||
        device_ordinal >= static_cast<int>(devices.size())) {
      *err = "device ordinal " + std::to_string(device_ordinal) +
             " out of range (" + std::to_string(devices.size()) +
             " addressable devices)";
      return nullptr;
    }
    auto* device = devices[device_ordinal];
    auto ms_or = device->default_memory_space();
    if (!ms_or.ok()) { *err = ms_or.status().ToString(); return nullptr; }

    std::vector<std::unique_ptr<xla::PjRtBuffer>> in_bufs;
    std::vector<xla::PjRtBuffer*> in_ptrs;
    const long long* d = dims;
    for (int a = 0; a < nargs; ++a) {
      std::vector<int64_t> shape(d, d + ndims[a]);
      d += ndims[a];
      auto buf_or = client->BufferFromHostBuffer(
          data[a], to_xla_type(dtypes[a]), shape, std::nullopt,
          xla::PjRtClient::HostBufferSemantics::kImmutableOnlyDuringCall,
          nullptr, ms_or.value(), nullptr);
      if (!buf_or.ok()) { *err = buf_or.status().ToString(); return nullptr; }
      in_bufs.push_back(std::move(buf_or).value());
      in_ptrs.push_back(in_bufs.back().get());
    }
    std::vector<std::vector<xla::PjRtBuffer*>> arg_lists = {in_ptrs};
    xla::ExecuteOptions exec_opts;
    exec_opts.untuple_result = true;
    auto out_or = exe->exe->Execute(absl::MakeSpan(arg_lists), exec_opts);
    if (!out_or.ok()) { *err = out_or.status().ToString(); return nullptr; }
    auto* r = new CppResults();
    r->bufs = std::move(out_or.value()[0]);
    return r;
  }
};

// ---------------------------------------------------------------------------
// PJRT C API backend (dlopen'd plugin, e.g. libtpu.so)
// ---------------------------------------------------------------------------

std::string capi_err(const PJRT_Api* api, PJRT_Error* e) {
  if (!e) return "";
  PJRT_Error_Message_Args m;
  std::memset(&m, 0, sizeof(m));
  m.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  m.error = e;
  api->PJRT_Error_Message(&m);
  std::string msg(m.message, m.message_size);
  PJRT_Error_Destroy_Args dd;
  std::memset(&dd, 0, sizeof(dd));
  dd.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dd.error = e;
  api->PJRT_Error_Destroy(&dd);
  return msg;
}

// Awaits and destroys the event; returns error message or "".
std::string capi_await(const PJRT_Api* api, PJRT_Event* ev) {
  if (!ev) return "";
  PJRT_Event_Await_Args aw;
  std::memset(&aw, 0, sizeof(aw));
  aw.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aw.event = ev;
  std::string msg = capi_err(api, api->PJRT_Event_Await(&aw));
  PJRT_Event_Destroy_Args dd;
  std::memset(&dd, 0, sizeof(dd));
  dd.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  dd.event = ev;
  api->PJRT_Event_Destroy(&dd);
  return msg;
}

PJRT_Buffer_Type to_capi_type(int dt) {
  switch (dt) {
    case TFR_F32: return PJRT_Buffer_Type_F32;
    case TFR_F64: return PJRT_Buffer_Type_F64;
    case TFR_I32: return PJRT_Buffer_Type_S32;
    case TFR_I64: return PJRT_Buffer_Type_S64;
    case TFR_BF16: return PJRT_Buffer_Type_BF16;
    case TFR_PRED: return PJRT_Buffer_Type_PRED;
  }
  return PJRT_Buffer_Type_INVALID;
}

int from_capi_type(PJRT_Buffer_Type t) {
  switch (t) {
    case PJRT_Buffer_Type_F32: return TFR_F32;
    case PJRT_Buffer_Type_F64: return TFR_F64;
    case PJRT_Buffer_Type_S32: return TFR_I32;
    case PJRT_Buffer_Type_S64: return TFR_I64;
    case PJRT_Buffer_Type_BF16: return TFR_BF16;
    case PJRT_Buffer_Type_PRED: return TFR_PRED;
    default: return 0;
  }
}

// Minimal serialized xla.CompileOptionsProto:
//   executable_build_options (field 3) {
//     num_replicas (field 4) = 1; num_partitions (field 5) = 1; }
const char kCompileOptionsProto[] = {0x1a, 0x04, 0x20, 0x01, 0x28, 0x01};

// Same proto with num_replicas = n (single-byte varint, n < 128).
std::string compile_options_proto(int n_replicas) {
  std::string p(kCompileOptionsProto, sizeof(kCompileOptionsProto));
  p[3] = static_cast<char>(n_replicas);
  return p;
}

// executable_build_options { num_replicas (4) = 1; num_partitions (5) = n;
// use_spmd_partitioning (6) = true } — the GSPMD compile request
// (n < 128 keeps every varint single-byte).
std::string compile_options_proto_spmd(int n_partitions) {
  std::string ebo;
  ebo += '\x20'; ebo += '\x01';                           // num_replicas=1
  ebo += '\x28'; ebo += static_cast<char>(n_partitions);  // num_partitions
  ebo += '\x30'; ebo += '\x01';                           // use_spmd=true
  std::string p;
  p += '\x1a';                                            // field 3, LEN
  p += static_cast<char>(ebo.size());
  p += ebo;
  return p;
}

struct CApiExe : ExeIface {
  const PJRT_Api* api = nullptr;
  PJRT_LoadedExecutable* exe = nullptr;
  ~CApiExe() override {
    if (exe) {
      PJRT_LoadedExecutable_Destroy_Args dd;
      std::memset(&dd, 0, sizeof(dd));
      dd.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
      dd.executable = exe;
      capi_err(api, api->PJRT_LoadedExecutable_Destroy(&dd));
    }
  }
};

// Shared meta query for a single PJRT_Buffer (results + detached bufs).
int capi_buffer_meta(const PJRT_Api* api, PJRT_Buffer* buf, int* dtype,
                     int* ndim, long long* dims) {
  PJRT_Buffer_ElementType_Args et;
  std::memset(&et, 0, sizeof(et));
  et.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
  et.buffer = buf;
  if (api->PJRT_Buffer_ElementType(&et)) return 2;
  *dtype = from_capi_type(et.type);
  PJRT_Buffer_Dimensions_Args dm;
  std::memset(&dm, 0, sizeof(dm));
  dm.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
  dm.buffer = buf;
  if (api->PJRT_Buffer_Dimensions(&dm)) return 2;
  if (dm.num_dims > 8) return 2;
  *ndim = static_cast<int>(dm.num_dims);
  for (size_t k = 0; k < dm.num_dims; ++k) dims[k] = dm.dims[k];
  return 0;
}

struct CApiBuf : BufIface {
  const PJRT_Api* api = nullptr;
  PJRT_Buffer* buf = nullptr;

  ~CApiBuf() override {
    if (buf) {
      PJRT_Buffer_Destroy_Args dd;
      std::memset(&dd, 0, sizeof(dd));
      dd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
      dd.buffer = buf;
      capi_err(api, api->PJRT_Buffer_Destroy(&dd));
    }
  }

  int meta(int* dtype, int* ndim, long long* dims) const override {
    return capi_buffer_meta(api, buf, dtype, ndim, dims);
  }
};

struct CApiResults : ResultsIface {
  const PJRT_Api* api = nullptr;
  std::vector<PJRT_Buffer*> bufs;

  ~CApiResults() override {
    for (auto* b : bufs) {
      if (!b) continue;  // released slots
      PJRT_Buffer_Destroy_Args dd;
      std::memset(&dd, 0, sizeof(dd));
      dd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
      dd.buffer = b;
      capi_err(api, api->PJRT_Buffer_Destroy(&dd));
    }
  }

  int count() const override { return static_cast<int>(bufs.size()); }

  BufIface* release(int i) override {
    if (i < 0 || i >= count() || !bufs[i]) return nullptr;
    auto* b = new CApiBuf();
    b->api = api;
    b->buf = bufs[i];
    bufs[i] = nullptr;  // slot emptied; meta/read now fail
    return b;
  }

  int meta(int i, int* dtype, int* ndim, long long* dims) const override {
    if (i < 0 || i >= count() || !bufs[i]) return 1;
    return capi_buffer_meta(api, bufs[i], dtype, ndim, dims);
  }

  int read(int i, void* dst, long long nbytes, std::string* err) override {
    if (i < 0 || i >= count() || !bufs[i]) {
      *err = "result index out of range or buffer released";
      return 1;
    }
    PJRT_Buffer_ToHostBuffer_Args th;
    std::memset(&th, 0, sizeof(th));
    th.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    th.src = bufs[i];
    th.dst = nullptr;  // query size
    if (auto* e = api->PJRT_Buffer_ToHostBuffer(&th)) {
      *err = capi_err(api, e);
      return 1;
    }
    if (static_cast<long long>(th.dst_size) != nbytes) {
      *err = "size mismatch: host needs " + std::to_string(th.dst_size) +
             " bytes, caller expects " + std::to_string(nbytes);
      return 1;
    }
    th.dst = dst;
    if (auto* e = api->PJRT_Buffer_ToHostBuffer(&th)) {
      *err = capi_err(api, e);
      return 1;
    }
    std::string msg = capi_await(api, th.event);
    if (!msg.empty()) { *err = msg; return 1; }
    return 0;
  }
};

struct CApiClient : ClientIface {
  void* dl = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  int device_ordinal = 0;

  ~CApiClient() override {
    if (client) {
      PJRT_Client_Destroy_Args dd;
      std::memset(&dd, 0, sizeof(dd));
      dd.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
      dd.client = client;
      capi_err(api, api->PJRT_Client_Destroy(&dd));
    }
    // The plugin stays loaded (dlclose of live XLA runtimes is unsafe).
  }

  std::string init(const std::string& path,
                   const std::vector<SpecOption>& options) {
    dl = dlopen(path.c_str(), RTLD_NOW | RTLD_LOCAL);
    if (!dl) return std::string("dlopen failed: ") + dlerror();
    using GetApiFn = const PJRT_Api* (*)();
    auto get_api = reinterpret_cast<GetApiFn>(dlsym(dl, "GetPjrtApi"));
    if (!get_api) return "plugin has no GetPjrtApi symbol";
    api = get_api();
    if (!api) return "GetPjrtApi returned null";
    PJRT_Plugin_Initialize_Args pi;
    std::memset(&pi, 0, sizeof(pi));
    pi.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    if (auto* e = api->PJRT_Plugin_Initialize(&pi)) {
      return "plugin init failed: " + capi_err(api, e);
    }
    // Spec options become PJRT NamedValues (int64 when numeric, string
    // otherwise — proxy plugins like axon reject bools for flags, so the
    // int encoding matches what jax's register_plugin sends).
    std::vector<PJRT_NamedValue> nvs(options.size());
    for (size_t i = 0; i < options.size(); ++i) {
      auto& nv = nvs[i];
      std::memset(&nv, 0, sizeof(nv));
      nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv.name = options[i].key.c_str();
      nv.name_size = options[i].key.size();
      if (options[i].is_int) {
        nv.type = PJRT_NamedValue_kInt64;
        nv.int64_value = options[i].int_value;
        nv.value_size = 1;
      } else {
        nv.type = PJRT_NamedValue_kString;
        nv.string_value = options[i].value.c_str();
        nv.value_size = options[i].value.size();
      }
    }
    PJRT_Client_Create_Args cc;
    std::memset(&cc, 0, sizeof(cc));
    cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
    cc.create_options = nvs.data();
    cc.num_options = nvs.size();
    if (auto* e = api->PJRT_Client_Create(&cc)) {
      return "client create failed: " + capi_err(api, e);
    }
    client = cc.client;
    return "";
  }

  int device_count() const override {
    PJRT_Client_AddressableDevices_Args ad;
    std::memset(&ad, 0, sizeof(ad));
    ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    ad.client = client;
    if (api->PJRT_Client_AddressableDevices(&ad)) return -1;
    return static_cast<int>(ad.num_addressable_devices);
  }

  std::string platform() const override {
    PJRT_Client_PlatformName_Args pn;
    std::memset(&pn, 0, sizeof(pn));
    pn.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
    pn.client = client;
    if (api->PJRT_Client_PlatformName(&pn)) return "?";
    return std::string(pn.platform_name, pn.platform_name_size);
  }

  ExeIface* compile(std::string_view module, std::string* err) override {
    return compile_fmt(module, "mlir", err);
  }

  ExeIface* compile_hlo(const std::string& hlo_proto, std::string* err,
                        int n_replicas = 1) override {
    return compile_fmt(hlo_proto, "hlo", err, n_replicas);
  }

  ExeIface* compile_fmt(std::string_view module, const char* format,
                        std::string* err, int n_replicas = 1,
                        int n_partitions = 1) {
    PJRT_Program prog;
    std::memset(&prog, 0, sizeof(prog));
    prog.struct_size = PJRT_Program_STRUCT_SIZE;
    prog.code = const_cast<char*>(module.data());
    prog.code_size = module.size();
    prog.format = format;
    prog.format_size = std::strlen(format);

    std::string opts = n_partitions > 1
        ? compile_options_proto_spmd(n_partitions)
        : compile_options_proto(n_replicas);
    PJRT_Client_Compile_Args ca;
    std::memset(&ca, 0, sizeof(ca));
    ca.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
    ca.client = client;
    ca.program = &prog;
    ca.compile_options = opts.data();
    ca.compile_options_size = opts.size();
    if (auto* e = api->PJRT_Client_Compile(&ca)) {
      *err = capi_err(api, e);
      return nullptr;
    }
    auto* ex = new CApiExe();
    ex->api = api;
    ex->exe = ca.executable;
    return ex;
  }

  ExeIface* compile_n(std::string_view module, int n_replicas,
                      std::string* err) override {
    if (n_replicas < 1 || n_replicas > 127 ||
        n_replicas > device_count()) {
      *err = "n_replicas " + std::to_string(n_replicas) +
             " out of range (1.." + std::to_string(device_count()) + ")";
      return nullptr;
    }
    return compile_fmt(module, "mlir", err, n_replicas);
  }

  ExeIface* compile_spmd(std::string_view module, int n_partitions,
                         std::string* err) override {
    if (n_partitions < 1 || n_partitions > 127 ||
        n_partitions > device_count()) {
      *err = "n_partitions " + std::to_string(n_partitions) +
             " out of range (1.." + std::to_string(device_count()) + ")";
      return nullptr;
    }
    return compile_fmt(module, "mlir", err, /*n_replicas=*/1, n_partitions);
  }

  ResultsIface* execute_replicated_mixed(ExeIface* exe_i, int n_replicas,
                                         int nargs, const int* dtypes,
                                         const int* ndims,
                                         const long long* dims,
                                         const void* const* data,
                                         BufIface* const* dev_bufs,
                                         std::string* err) override {
    auto* exe = static_cast<CApiExe*>(exe_i);
    // the executable's addressable devices, in replica order
    PJRT_LoadedExecutable_AddressableDevices_Args ad;
    std::memset(&ad, 0, sizeof(ad));
    ad.struct_size =
        PJRT_LoadedExecutable_AddressableDevices_Args_STRUCT_SIZE;
    ad.executable = exe->exe;
    if (auto* e = api->PJRT_LoadedExecutable_AddressableDevices(&ad)) {
      *err = capi_err(api, e);
      return nullptr;
    }
    if (static_cast<int>(ad.num_addressable_devices) < n_replicas) {
      *err = "executable has " + std::to_string(ad.num_addressable_devices)
             + " addressable devices, need " + std::to_string(n_replicas);
      return nullptr;
    }

    std::vector<PJRT_Buffer*> in_bufs;  // only buffers we created here
    auto destroy_inputs = [&]() {
      for (auto* b : in_bufs) {
        PJRT_Buffer_Destroy_Args dd;
        std::memset(&dd, 0, sizeof(dd));
        dd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
        dd.buffer = b;
        capi_err(api, api->PJRT_Buffer_Destroy(&dd));
      }
    };
    std::vector<std::vector<PJRT_Buffer*>> arg_lists(n_replicas);
    for (int r = 0; r < n_replicas; ++r) {
      PJRT_Device* device = ad.addressable_devices[r];
      const long long* d = dims;
      for (int a = 0; a < nargs; ++a) {
        std::vector<int64_t> shape(d, d + ndims[a]);
        d += ndims[a];
        if (dev_bufs && dev_bufs[r * nargs + a]) {
          // device-resident input: borrowed (caller keeps ownership;
          // not added to in_bufs, so never destroyed here)
          arg_lists[r].push_back(
              static_cast<CApiBuf*>(dev_bufs[r * nargs + a])->buf);
          continue;
        }
        PJRT_Client_BufferFromHostBuffer_Args bh;
        std::memset(&bh, 0, sizeof(bh));
        bh.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
        bh.client = client;
        bh.data = data[r * nargs + a];
        bh.type = to_capi_type(dtypes[a]);
        bh.dims = shape.data();
        bh.num_dims = shape.size();
        bh.host_buffer_semantics =
            PJRT_HostBufferSemantics_kImmutableOnlyDuringCall;
        bh.device = device;
        if (auto* e = api->PJRT_Client_BufferFromHostBuffer(&bh)) {
          *err = capi_err(api, e);
          destroy_inputs();
          return nullptr;
        }
        std::string msg = capi_await(api, bh.done_with_host_buffer);
        in_bufs.push_back(bh.buffer);
        arg_lists[r].push_back(bh.buffer);
        if (!msg.empty()) {
          *err = msg;
          destroy_inputs();
          return nullptr;
        }
      }
    }

    PJRT_LoadedExecutable_GetExecutable_Args ge;
    std::memset(&ge, 0, sizeof(ge));
    ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    ge.loaded_executable = exe->exe;
    if (auto* e = api->PJRT_LoadedExecutable_GetExecutable(&ge)) {
      *err = capi_err(api, e);
      destroy_inputs();
      return nullptr;
    }
    PJRT_Executable_NumOutputs_Args no;
    std::memset(&no, 0, sizeof(no));
    no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
    no.executable = ge.executable;
    if (auto* e = api->PJRT_Executable_NumOutputs(&no)) {
      *err = capi_err(api, e);
      destroy_inputs();
      return nullptr;
    }

    PJRT_ExecuteOptions opts;
    std::memset(&opts, 0, sizeof(opts));
    opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

    std::vector<std::vector<PJRT_Buffer*>> outs(
        n_replicas, std::vector<PJRT_Buffer*>(no.num_outputs, nullptr));
    std::vector<PJRT_Buffer* const*> arg_ptrs(n_replicas);
    std::vector<PJRT_Buffer**> out_ptrs(n_replicas);
    for (int r = 0; r < n_replicas; ++r) {
      arg_ptrs[r] = arg_lists[r].data();
      out_ptrs[r] = outs[r].data();
    }
    std::vector<PJRT_Event*> done(n_replicas, nullptr);

    PJRT_LoadedExecutable_Execute_Args ex;
    std::memset(&ex, 0, sizeof(ex));
    ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    ex.executable = exe->exe;
    ex.options = &opts;
    ex.argument_lists = arg_ptrs.data();
    ex.num_devices = static_cast<size_t>(n_replicas);
    ex.num_args = static_cast<size_t>(nargs);
    ex.output_lists = out_ptrs.data();
    ex.device_complete_events = done.data();
    ex.execute_device = nullptr;  // multi-device launch
    if (auto* e = api->PJRT_LoadedExecutable_Execute(&ex)) {
      *err = capi_err(api, e);
      destroy_inputs();
      return nullptr;
    }
    std::string msg;
    for (int r = 0; r < n_replicas; ++r) {
      std::string m = capi_await(api, done[r]);
      if (!m.empty() && msg.empty()) msg = m;
    }
    destroy_inputs();
    auto* res = new CApiResults();
    res->api = api;
    for (int r = 0; r < n_replicas; ++r) {
      for (auto* b : outs[r]) res->bufs.push_back(b);
    }
    if (!msg.empty()) {
      *err = msg;
      delete res;
      return nullptr;
    }
    return res;
  }

  ResultsIface* execute(ExeIface* exe_i, int nargs, const int* dtypes,
                        const int* ndims, const long long* dims,
                        const void* const* data, std::string* err) override {
    auto* exe = static_cast<CApiExe*>(exe_i);

    PJRT_Client_AddressableDevices_Args ad;
    std::memset(&ad, 0, sizeof(ad));
    ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    ad.client = client;
    if (auto* e = api->PJRT_Client_AddressableDevices(&ad)) {
      *err = capi_err(api, e);
      return nullptr;
    }
    if (device_ordinal < 0 ||
        static_cast<size_t>(device_ordinal) >= ad.num_addressable_devices) {
      *err = "device ordinal " + std::to_string(device_ordinal) +
             " out of range (" + std::to_string(ad.num_addressable_devices) +
             " addressable devices)";
      return nullptr;
    }
    PJRT_Device* device = ad.addressable_devices[device_ordinal];

    std::vector<PJRT_Buffer*> in_bufs;
    auto destroy_inputs = [&]() {
      for (auto* b : in_bufs) {
        PJRT_Buffer_Destroy_Args dd;
        std::memset(&dd, 0, sizeof(dd));
        dd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
        dd.buffer = b;
        capi_err(api, api->PJRT_Buffer_Destroy(&dd));
      }
    };
    const long long* d = dims;
    for (int a = 0; a < nargs; ++a) {
      std::vector<int64_t> shape(d, d + ndims[a]);
      d += ndims[a];
      PJRT_Client_BufferFromHostBuffer_Args bh;
      std::memset(&bh, 0, sizeof(bh));
      bh.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
      bh.client = client;
      bh.data = data[a];
      bh.type = to_capi_type(dtypes[a]);
      bh.dims = shape.data();
      bh.num_dims = shape.size();
      bh.host_buffer_semantics =
          PJRT_HostBufferSemantics_kImmutableOnlyDuringCall;
      bh.device = device;
      if (auto* e = api->PJRT_Client_BufferFromHostBuffer(&bh)) {
        *err = capi_err(api, e);
        destroy_inputs();
        return nullptr;
      }
      std::string msg = capi_await(api, bh.done_with_host_buffer);
      in_bufs.push_back(bh.buffer);
      if (!msg.empty()) {
        *err = msg;
        destroy_inputs();
        return nullptr;
      }
    }

    // number of outputs
    PJRT_LoadedExecutable_GetExecutable_Args ge;
    std::memset(&ge, 0, sizeof(ge));
    ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    ge.loaded_executable = exe->exe;
    if (auto* e = api->PJRT_LoadedExecutable_GetExecutable(&ge)) {
      *err = capi_err(api, e);
      destroy_inputs();
      return nullptr;
    }
    PJRT_Executable_NumOutputs_Args no;
    std::memset(&no, 0, sizeof(no));
    no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
    no.executable = ge.executable;
    if (auto* e = api->PJRT_Executable_NumOutputs(&no)) {
      *err = capi_err(api, e);
      destroy_inputs();
      return nullptr;
    }

    PJRT_ExecuteOptions opts;
    std::memset(&opts, 0, sizeof(opts));
    opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

    std::vector<PJRT_Buffer*> outs(no.num_outputs, nullptr);
    PJRT_Buffer* const* arg_list = in_bufs.data();
    PJRT_Buffer** out_list = outs.data();
    PJRT_Event* done = nullptr;

    PJRT_LoadedExecutable_Execute_Args ex;
    std::memset(&ex, 0, sizeof(ex));
    ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    ex.executable = exe->exe;
    ex.options = &opts;
    ex.argument_lists = &arg_list;
    ex.num_devices = 1;
    ex.num_args = static_cast<size_t>(nargs);
    ex.output_lists = &out_list;
    ex.device_complete_events = &done;
    ex.execute_device = device;
    if (auto* e = api->PJRT_LoadedExecutable_Execute(&ex)) {
      *err = capi_err(api, e);
      destroy_inputs();
      return nullptr;
    }
    std::string msg = capi_await(api, done);
    destroy_inputs();
    auto* r = new CApiResults();
    r->api = api;
    r->bufs = std::move(outs);
    if (!msg.empty()) {
      *err = msg;
      delete r;  // destroys any produced output buffers
      return nullptr;
    }
    return r;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C interface
// ---------------------------------------------------------------------------

struct tfr_pjrt_client {
  std::unique_ptr<ClientIface> impl;
};
struct tfr_pjrt_exe {
  std::unique_ptr<ExeIface> impl;
};
struct tfr_pjrt_results {
  std::unique_ptr<ResultsIface> impl;
};
struct tfr_pjrt_buffer {
  std::unique_ptr<BufIface> impl;
};

extern "C" {

tfr_pjrt_client* tfr_pjrt_client_create(const char* spec, char* err,
                                        int errlen) {
  std::string s(spec ? spec : "");
  try {
    std::vector<SpecOption> options = parse_spec_options(&s);
    int ordinal = take_device_ordinal(&options);
    if (s == "cpu" || s.rfind("cpu:", 0) == 0) {
      xla::CpuClientOptions opts;
      opts.cpu_device_count = 1;
      if (s.size() > 4) opts.cpu_device_count = std::stoi(s.substr(4));
      auto c_or = xla::GetXlaPjrtCpuClient(opts);
      if (!c_or.ok()) {
        set_err(err, errlen, c_or.status().ToString());
        return nullptr;
      }
      auto* c = new CppClient();
      c->client = std::move(c_or).value();
      c->device_ordinal = ordinal;
      auto* out = new tfr_pjrt_client();
      out->impl.reset(c);
      return out;
    }
    if (s.rfind("plugin:", 0) == 0) {
      auto* c = new CApiClient();
      c->device_ordinal = ordinal;
      std::string msg = c->init(s.substr(7), options);
      if (!msg.empty()) {
        set_err(err, errlen, msg);
        delete c;
        return nullptr;
      }
      auto* out = new tfr_pjrt_client();
      out->impl.reset(c);
      return out;
    }
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return nullptr;
  }
  set_err(err, errlen, "unknown backend spec: " + s +
                       " (expected cpu[:n] or plugin:<path>)");
  return nullptr;
}

void tfr_pjrt_client_destroy(tfr_pjrt_client* c) { delete c; }

int tfr_pjrt_client_device_count(tfr_pjrt_client* c) {
  return c->impl->device_count();
}

int tfr_pjrt_client_platform(tfr_pjrt_client* c, char* out, int outlen) {
  std::string p = c->impl->platform();
  int n = static_cast<int>(p.size());
  if (out && outlen > 0) {
    std::snprintf(out, static_cast<size_t>(outlen), "%s", p.c_str());
  }
  return n;
}

tfr_pjrt_exe* tfr_pjrt_compile(tfr_pjrt_client* c, const char* module_bytes,
                               long module_len, char* err, int errlen) {
  std::string errmsg;
  ExeIface* e = c->impl->compile(
      std::string_view(module_bytes, static_cast<size_t>(module_len)),
      &errmsg);
  if (!e) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_exe();
  out->impl.reset(e);
  return out;
}

tfr_pjrt_exe* tfr_pjrt_compile_dynamic(
    tfr_pjrt_client* c, const char* module_bytes, long module_len,
    int cc_version, const char* platforms_csv, const char* select_platform,
    int nargs, const int* dtypes, const int* ndims, const long long* dims,
    char* err, int errlen) {
  return tfr_pjrt_compile_dynamic_n(
      c, module_bytes, module_len, cc_version, platforms_csv,
      select_platform, nargs, dtypes, ndims, dims, 1, err, errlen);
}

tfr_pjrt_exe* tfr_pjrt_compile_dynamic_n(
    tfr_pjrt_client* c, const char* module_bytes, long module_len,
    int cc_version, const char* platforms_csv, const char* select_platform,
    int nargs, const int* dtypes, const int* ndims, const long long* dims,
    int n_replicas, char* err, int errlen) {
  std::vector<std::string> platforms;
  std::string csv(platforms_csv ? platforms_csv : "");
  size_t pos = 0;
  while (pos <= csv.size() && !csv.empty()) {
    auto comma = csv.find(',', pos);
    platforms.push_back(csv.substr(
        pos, comma == std::string::npos ? std::string::npos : comma - pos));
    if (comma == std::string::npos) break;
    pos = comma + 1;
  }
  auto hlo_or = refine_to_hlo_proto(
      std::string_view(module_bytes, static_cast<size_t>(module_len)),
      cc_version, platforms,
      std::string(select_platform ? select_platform : ""), nargs, dtypes,
      ndims, dims);
  if (!hlo_or.ok()) {
    set_err(err, errlen, hlo_or.status().ToString());
    return nullptr;
  }
  std::string errmsg;
  ExeIface* e = c->impl->compile_hlo(hlo_or.value(), &errmsg, n_replicas);
  if (!e) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_exe();
  out->impl.reset(e);
  return out;
}

tfr_pjrt_exe* tfr_pjrt_compile_n(tfr_pjrt_client* c,
                                 const char* module_bytes, long module_len,
                                 int n_replicas, char* err, int errlen) {
  std::string errmsg;
  ExeIface* e = c->impl->compile_n(
      std::string_view(module_bytes, static_cast<size_t>(module_len)),
      n_replicas, &errmsg);
  if (!e) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_exe();
  out->impl.reset(e);
  return out;
}

tfr_pjrt_exe* tfr_pjrt_compile_spmd(tfr_pjrt_client* c,
                                    const char* module_bytes,
                                    long module_len, int n_partitions,
                                    char* err, int errlen) {
  std::string errmsg;
  ExeIface* e = c->impl->compile_spmd(
      std::string_view(module_bytes, static_cast<size_t>(module_len)),
      n_partitions, &errmsg);
  if (!e) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_exe();
  out->impl.reset(e);
  return out;
}

tfr_pjrt_results* tfr_pjrt_execute_replicated(
    tfr_pjrt_client* c, tfr_pjrt_exe* e, int n_replicas, int nargs,
    const int* dtypes, const int* ndims, const long long* dims,
    const void* const* data, char* err, int errlen) {
  for (int a = 0; a < nargs; ++a) {
    if (dtype_size(dtypes[a]) == 0) {
      set_err(err, errlen,
              "unsupported dtype code " + std::to_string(dtypes[a]));
      return nullptr;
    }
  }
  std::string errmsg;
  ResultsIface* r = c->impl->execute_replicated(
      e->impl.get(), n_replicas, nargs, dtypes, ndims, dims, data, &errmsg);
  if (!r) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_results();
  out->impl.reset(r);
  return out;
}

void tfr_pjrt_exe_destroy(tfr_pjrt_exe* e) { delete e; }

tfr_pjrt_results* tfr_pjrt_execute(tfr_pjrt_client* c, tfr_pjrt_exe* e,
                                   int nargs, const int* dtypes,
                                   const int* ndims, const long long* dims,
                                   const void* const* data, char* err,
                                   int errlen) {
  for (int a = 0; a < nargs; ++a) {
    if (dtype_size(dtypes[a]) == 0) {
      set_err(err, errlen,
              "unsupported dtype code " + std::to_string(dtypes[a]));
      return nullptr;
    }
  }
  std::string errmsg;
  ResultsIface* r =
      c->impl->execute(e->impl.get(), nargs, dtypes, ndims, dims, data,
                       &errmsg);
  if (!r) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_results();
  out->impl.reset(r);
  return out;
}

int tfr_pjrt_results_count(tfr_pjrt_results* r) { return r->impl->count(); }

int tfr_pjrt_result_meta(tfr_pjrt_results* r, int i, int* dtype, int* ndim,
                         long long* dims) {
  return r->impl->meta(i, dtype, ndim, dims);
}

int tfr_pjrt_result_read(tfr_pjrt_results* r, int i, void* dst,
                         long long nbytes, char* err, int errlen) {
  std::string errmsg;
  int rc = r->impl->read(i, dst, nbytes, &errmsg);
  if (rc) set_err(err, errlen, errmsg);
  return rc;
}

void tfr_pjrt_results_destroy(tfr_pjrt_results* r) { delete r; }

tfr_pjrt_buffer* tfr_pjrt_result_release_buffer(tfr_pjrt_results* r,
                                                int i) {
  BufIface* b = r->impl->release(i);
  if (!b) return nullptr;
  auto* out = new tfr_pjrt_buffer();
  out->impl.reset(b);
  return out;
}

int tfr_pjrt_buffer_meta(tfr_pjrt_buffer* b, int* dtype, int* ndim,
                         long long* dims) {
  return b->impl->meta(dtype, ndim, dims);
}

void tfr_pjrt_buffer_destroy(tfr_pjrt_buffer* b) { delete b; }

tfr_pjrt_results* tfr_pjrt_execute_replicated_mixed(
    tfr_pjrt_client* c, tfr_pjrt_exe* e, int n_replicas, int nargs,
    const int* dtypes, const int* ndims, const long long* dims,
    const void* const* data, tfr_pjrt_buffer* const* dev_bufs, char* err,
    int errlen) {
  for (int a = 0; a < nargs; ++a) {
    if (dtype_size(dtypes[a]) == 0) {
      set_err(err, errlen,
              "unsupported dtype code " + std::to_string(dtypes[a]));
      return nullptr;
    }
  }
  std::vector<BufIface*> devs;
  if (dev_bufs) {
    devs.resize(static_cast<size_t>(n_replicas) * nargs, nullptr);
    for (size_t i = 0; i < devs.size(); ++i) {
      if (dev_bufs[i]) devs[i] = dev_bufs[i]->impl.get();
    }
  }
  std::string errmsg;
  ResultsIface* r = c->impl->execute_replicated_mixed(
      e->impl.get(), n_replicas, nargs, dtypes, ndims, dims, data,
      dev_bufs ? devs.data() : nullptr, &errmsg);
  if (!r) {
    set_err(err, errlen, errmsg);
    return nullptr;
  }
  auto* out = new tfr_pjrt_results();
  out->impl.reset(r);
  return out;
}

}  // extern "C"
