// JVM host demo: a Java program as the EXECUTOR HOST.
//
// The reference's first-class host WAS a JVM — Scala code driving the
// libtensorflow C++ runtime through javacpp JNI bindings
// (PythonInterface.scala:23-81 -> TensorFlowOps.scala:46-64). This
// program replays native/host_demo.cpp from Java: no Python, no jax —
// it parses a TFTPU1 blob serialized by the Python driver
// (tensorframes_tpu/computation.py:246-341), compiles the raw
// dynamic-shape StableHLO module at a concrete row count through the
// C ABI (tfrpjrt.h, reached via the thin JNI glue in tfr_jni.cpp), and
// executes it on rows it fabricates.
//
// Usage:  java -Dtfr.jni=<path/libtfrjni.so> TfrHostDemo <blob> <rows>
// Exit 0 and a final "JVM_HOST_OK" line on success.
//
// Build:  make -C native jni   (needs a JDK; links libtfrpjrt.so)

import java.nio.charset.StandardCharsets;
import java.nio.file.Files;
import java.nio.file.Paths;

public final class TfrHostDemo {
    static {
        System.load(System.getProperty("tfr.jni"));
    }

    // thin JNI surface over tfrpjrt.h (handles are opaque longs);
    // specialized to the demo's one-rank-1-f64-argument shape — the
    // general host surface is the C ABI itself
    private static native long clientCreate(String spec);
    private static native void clientDestroy(long client);
    private static native String clientPlatform(long client);
    private static native int deviceCount(long client);
    private static native long compileDynamicF64(
        long client, byte[] module, int ccVersion, String platformsCsv,
        String selectPlatform, long rows);
    private static native void exeDestroy(long exe);
    private static native double[] executeF64(long client, long exe,
                                              double[] x);

    // -- TFTPU1 header scanning (the fixed format of computation.py) ----

    private static long scanLong(String header, String key, long fallback) {
        int pos = header.indexOf("\"" + key + "\":");
        if (pos < 0) return fallback;
        int start = header.indexOf(':', pos) + 1;
        while (start < header.length()
               && header.charAt(start) == ' ') start++;
        int end = start;
        while (end < header.length()
               && (Character.isDigit(header.charAt(end))
                   || header.charAt(end) == '-')) end++;
        return Long.parseLong(header.substring(start, end));
    }

    // ["cpu", "tpu"] -> "cpu,tpu"
    private static String scanStringListCsv(String header, String key) {
        int pos = header.indexOf("\"" + key + "\":");
        if (pos < 0) return "";
        int open = header.indexOf('[', pos);
        int close = header.indexOf(']', open);
        if (open < 0 || close < 0) return "";
        StringBuilder out = new StringBuilder();
        int i = open;
        while (i < close) {
            int q1 = header.indexOf('"', i);
            if (q1 < 0 || q1 > close) break;
            int q2 = header.indexOf('"', q1 + 1);
            if (q2 < 0 || q2 > close) break;
            if (out.length() > 0) out.append(',');
            out.append(header, q1 + 1, q2);
            i = q2 + 1;
        }
        return out.toString();
    }

    public static void main(String[] args) throws Exception {
        if (args.length < 2) {
            System.err.println("usage: TfrHostDemo <tftpu1-blob> <rows>");
            System.exit(2);
        }
        byte[] blob = Files.readAllBytes(Paths.get(args[0]));
        long rows = Long.parseLong(args[1]);

        byte[] magic = "TFTPU1\0".getBytes(StandardCharsets.US_ASCII);
        for (int i = 0; i < magic.length; i++) {
            if (blob.length <= i || blob[i] != magic[i]) {
                System.err.println("not a TFTPU1 blob");
                System.exit(2);
            }
        }
        // header length: little-endian uint32 after the magic
        int hlen = (blob[7] & 0xFF) | ((blob[8] & 0xFF) << 8)
                 | ((blob[9] & 0xFF) << 16) | ((blob[10] & 0xFF) << 24);
        String header = new String(blob, 11, hlen,
                                   StandardCharsets.UTF_8);
        int payloadOff = 11 + hlen;
        long moduleLen = scanLong(header, "module_len", -1);
        long ccVersion = scanLong(header, "cc_version", -1);
        String platforms = scanStringListCsv(header, "platforms");
        String argDtype = scanStringListCsv(header, "arg_dtypes");
        int comma = argDtype.indexOf(',');
        if (comma >= 0) argDtype = argDtype.substring(0, comma);
        if (moduleLen < 0 || ccVersion < 0) {
            System.err.println(
                "blob has no native section (pre-native format?)");
            System.exit(2);
        }
        if (!argDtype.equals("float64")) {
            System.err.println("demo supports float64 args, got "
                               + argDtype);
            System.exit(2);
        }
        byte[] module = new byte[(int) moduleLen];
        System.arraycopy(blob, payloadOff, module, 0, (int) moduleLen);
        System.err.println("[jvm_host] header: module_len=" + moduleLen
                           + " cc_version=" + ccVersion
                           + " platforms=" + platforms);

        long client = clientCreate("cpu");
        if (client == 0) System.exit(1);
        String plat = clientPlatform(client);
        System.err.println("[jvm_host] platform=" + plat
                           + " devices=" + deviceCount(client));

        long exe = compileDynamicF64(client, module, (int) ccVersion,
                                     platforms, plat, rows);
        if (exe == 0) {
            clientDestroy(client);
            System.exit(1);
        }
        double[] x = new double[(int) rows];
        for (int i = 0; i < rows; i++) x[i] = i;
        double[] out = executeF64(client, exe, x);
        if (out == null) {
            exeDestroy(exe);
            clientDestroy(client);
            System.exit(1);
        }
        System.out.printf("out[0] dtype=f64 elems=%d first=%.6f "
                          + "last=%.6f%n", out.length,
                          out.length > 0 ? out[0] : 0.0,
                          out.length > 0 ? out[out.length - 1] : 0.0);
        exeDestroy(exe);
        clientDestroy(client);
        System.out.println("JVM_HOST_OK");
    }
}
