// JNI glue: the thin shim between TfrHostDemo.java and libtfrpjrt.so.
//
// The reference's equivalent was javacpp's generated JNI bindings around
// libtensorflow (project/Dependencies.scala:36-43); this is the same
// boundary hand-written for the demo's surface — opaque handles travel
// as jlong, errors print to stderr and return 0/null (the Java side
// exits non-zero). Specialized to one rank-1 float64 argument; the
// general host surface is the C ABI itself (tfrpjrt.h).
//
// Build: make -C native jni   (needs JAVA_HOME with include/jni.h)

#include <jni.h>

#include <cstdio>
#include <vector>

#include "../tfrpjrt.h"

namespace {
constexpr int kErrLen = 4096;
}

extern "C" {

JNIEXPORT jlong JNICALL Java_TfrHostDemo_clientCreate(
    JNIEnv* env, jclass, jstring spec) {
  const char* s = env->GetStringUTFChars(spec, nullptr);
  char err[kErrLen] = {0};
  tfr_pjrt_client* c = tfr_pjrt_client_create(s, err, kErrLen);
  env->ReleaseStringUTFChars(spec, s);
  if (!c) std::fprintf(stderr, "client create failed: %s\n", err);
  return reinterpret_cast<jlong>(c);
}

JNIEXPORT void JNICALL Java_TfrHostDemo_clientDestroy(
    JNIEnv*, jclass, jlong client) {
  tfr_pjrt_client_destroy(reinterpret_cast<tfr_pjrt_client*>(client));
}

JNIEXPORT jstring JNICALL Java_TfrHostDemo_clientPlatform(
    JNIEnv* env, jclass, jlong client) {
  char plat[64] = {0};
  tfr_pjrt_client_platform(reinterpret_cast<tfr_pjrt_client*>(client),
                           plat, sizeof(plat));
  return env->NewStringUTF(plat);
}

JNIEXPORT jint JNICALL Java_TfrHostDemo_deviceCount(
    JNIEnv*, jclass, jlong client) {
  return tfr_pjrt_client_device_count(
      reinterpret_cast<tfr_pjrt_client*>(client));
}

JNIEXPORT jlong JNICALL Java_TfrHostDemo_compileDynamicF64(
    JNIEnv* env, jclass, jlong client, jbyteArray module, jint cc_version,
    jstring platforms_csv, jstring select_platform, jlong rows) {
  jsize mlen = env->GetArrayLength(module);
  jbyte* mbytes = env->GetByteArrayElements(module, nullptr);
  const char* csv = env->GetStringUTFChars(platforms_csv, nullptr);
  const char* sel = env->GetStringUTFChars(select_platform, nullptr);
  int dtypes[1] = {TFR_F64};
  int ndims[1] = {1};
  long long dims[1] = {static_cast<long long>(rows)};
  char err[kErrLen] = {0};
  tfr_pjrt_exe* exe = tfr_pjrt_compile_dynamic(
      reinterpret_cast<tfr_pjrt_client*>(client),
      reinterpret_cast<const char*>(mbytes), static_cast<long>(mlen),
      static_cast<int>(cc_version), csv, sel, 1, dtypes, ndims, dims,
      err, kErrLen);
  env->ReleaseStringUTFChars(select_platform, sel);
  env->ReleaseStringUTFChars(platforms_csv, csv);
  env->ReleaseByteArrayElements(module, mbytes, JNI_ABORT);
  if (!exe) std::fprintf(stderr, "compile failed: %s\n", err);
  return reinterpret_cast<jlong>(exe);
}

JNIEXPORT void JNICALL Java_TfrHostDemo_exeDestroy(
    JNIEnv*, jclass, jlong exe) {
  tfr_pjrt_exe_destroy(reinterpret_cast<tfr_pjrt_exe*>(exe));
}

JNIEXPORT jdoubleArray JNICALL Java_TfrHostDemo_executeF64(
    JNIEnv* env, jclass, jlong client, jlong exe, jdoubleArray x) {
  jsize rows = env->GetArrayLength(x);
  jdouble* xv = env->GetDoubleArrayElements(x, nullptr);
  int dtypes[1] = {TFR_F64};
  int ndims[1] = {1};
  long long dims[1] = {static_cast<long long>(rows)};
  const void* data[1] = {xv};
  char err[kErrLen] = {0};
  tfr_pjrt_results* res = tfr_pjrt_execute(
      reinterpret_cast<tfr_pjrt_client*>(client),
      reinterpret_cast<tfr_pjrt_exe*>(exe), 1, dtypes, ndims, dims, data,
      err, kErrLen);
  env->ReleaseDoubleArrayElements(x, xv, JNI_ABORT);
  if (!res) {
    std::fprintf(stderr, "execute failed: %s\n", err);
    return nullptr;
  }
  if (tfr_pjrt_results_count(res) < 1) {
    std::fprintf(stderr, "no results\n");
    tfr_pjrt_results_destroy(res);
    return nullptr;
  }
  int odt = 0, ondim = 0;
  long long odims[8] = {0};
  if (tfr_pjrt_result_meta(res, 0, &odt, &ondim, odims) ||
      odt != TFR_F64) {
    std::fprintf(stderr, "result 0: meta failed or not f64 (%d)\n", odt);
    tfr_pjrt_results_destroy(res);
    return nullptr;
  }
  long long elems = 1;
  for (int d = 0; d < ondim; ++d) elems *= odims[d];
  std::vector<double> out(static_cast<size_t>(elems));
  if (tfr_pjrt_result_read(res, 0, out.data(), elems * 8, err, kErrLen)) {
    std::fprintf(stderr, "result read failed: %s\n", err);
    tfr_pjrt_results_destroy(res);
    return nullptr;
  }
  tfr_pjrt_results_destroy(res);
  jdoubleArray jout = env->NewDoubleArray(static_cast<jsize>(elems));
  if (!jout) return nullptr;
  env->SetDoubleArrayRegion(jout, 0, static_cast<jsize>(elems),
                            out.data());
  return jout;
}

}  // extern "C"
