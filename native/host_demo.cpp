// Second-host-language demo: a C++ program as the EXECUTOR HOST.
//
// The reference's native core served a JVM host through javacpp
// (PythonInterface.scala:23-81 -> TensorFlowOps.scala:46-64); the claim
// "any host can call this framework's core through the C ABI" is proven
// here the same way: this program contains NO Python and NO jax. It
//
//   1. reads a TFTPU1 blob (a computation serialized by the Python
//      DRIVER via Computation.serialize()),
//   2. parses the blob's JSON header with a few string scans (the format
//      is this framework's own, tensorframes_tpu/computation.py:246-341:
//      magic + header length + JSON + raw StableHLO module + jax.export
//      payload),
//   3. compiles the raw dynamic-shape module at a concrete row count
//      through tfr_pjrt_compile_dynamic (shape refinement happens inside
//      the native core), and
//   4. executes it on rows it fabricates, printing the outputs.
//
// Usage: host_demo <blob-path> <rows>
// Exit 0 and a final "HOST_DEMO_OK" line on success.
//
// Build: make -C native host_demo    (links libtfrpjrt.so)

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "tfrpjrt.h"

namespace {

// -- minimal header scanning (our own fixed format, not general JSON) ----

long scan_long(const std::string& s, const std::string& key, long fallback) {
  auto pos = s.find("\"" + key + "\":");
  if (pos == std::string::npos) return fallback;
  pos = s.find(':', pos);
  return std::strtol(s.c_str() + pos + 1, nullptr, 10);
}

// ["cpu", "tpu"] -> "cpu,tpu"
std::string scan_string_list_csv(const std::string& s,
                                 const std::string& key) {
  auto pos = s.find("\"" + key + "\":");
  if (pos == std::string::npos) return "";
  auto open = s.find('[', pos);
  auto close = s.find(']', open);
  if (open == std::string::npos || close == std::string::npos) return "";
  std::string out;
  size_t i = open;
  while (i < close) {
    auto q1 = s.find('"', i);
    if (q1 == std::string::npos || q1 > close) break;
    auto q2 = s.find('"', q1 + 1);
    if (q2 == std::string::npos || q2 > close) break;  // unterminated
    if (!out.empty()) out += ",";
    out += s.substr(q1 + 1, q2 - q1 - 1);
    i = q2 + 1;
  }
  return out;
}

int dtype_code_from_name(const std::string& name) {
  if (name == "float32") return TFR_F32;
  if (name == "float64") return TFR_F64;
  if (name == "int32") return TFR_I32;
  if (name == "int64") return TFR_I64;
  if (name == "bool") return TFR_PRED;
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <tftpu1-blob> <rows>\n", argv[0]);
    return 2;
  }
  const char* blob_path = argv[1];
  char* rows_end = nullptr;
  const long rows = std::strtol(argv[2], &rows_end, 10);
  if (rows_end == argv[2] || *rows_end != '\0' || rows <= 0) {
    std::fprintf(stderr, "rows must be a positive integer, got %s\n",
                 argv[2]);
    return 2;
  }

  std::ifstream f(blob_path, std::ios::binary);
  if (!f) {
    std::fprintf(stderr, "cannot read %s\n", blob_path);
    return 2;
  }
  std::string blob((std::istreambuf_iterator<char>(f)),
                   std::istreambuf_iterator<char>());
  const std::string magic("TFTPU1\0", 7);  // _MAGIC, computation.py:46
  if (blob.size() < magic.size() + 4 ||
      blob.compare(0, magic.size(), magic) != 0) {
    std::fprintf(stderr, "not a TFTPU1 blob\n");
    return 2;
  }
  unsigned int hlen = 0;
  std::memcpy(&hlen, blob.data() + magic.size(), 4);  // little-endian host
  const size_t payload_off = magic.size() + 4 + hlen;
  if (payload_off > blob.size()) {
    std::fprintf(stderr, "truncated TFTPU1 blob (header says %u bytes)\n",
                 hlen);
    return 2;
  }
  const std::string header = blob.substr(magic.size() + 4, hlen);

  const long module_len = scan_long(header, "module_len", -1);
  const long cc_version = scan_long(header, "cc_version", -1);
  const std::string platforms = scan_string_list_csv(header, "platforms");
  const std::string arg_dtype_name =
      scan_string_list_csv(header, "arg_dtypes");  // first entry wins below
  if (module_len < 0 || cc_version < 0) {
    std::fprintf(stderr, "blob has no native section (pre-native format?)\n");
    return 2;
  }
  if (payload_off + static_cast<size_t>(module_len) > blob.size()) {
    std::fprintf(stderr,
                 "truncated TFTPU1 blob (module section says %ld bytes)\n",
                 module_len);
    return 2;
  }
  std::string first_dtype = arg_dtype_name.substr(
      0, arg_dtype_name.find(','));
  const int dtype = dtype_code_from_name(first_dtype);
  if (dtype == 0) {
    std::fprintf(stderr, "unsupported arg dtype %s\n", first_dtype.c_str());
    return 2;
  }
  std::fprintf(stderr,
               "[host_demo] header: module_len=%ld cc_version=%ld "
               "platforms=%s arg_dtype=%s\n",
               module_len, cc_version, platforms.c_str(),
               first_dtype.c_str());

  char err[4096] = {0};
  tfr_pjrt_client* client = tfr_pjrt_client_create("cpu", err, sizeof(err));
  if (!client) {
    std::fprintf(stderr, "client create failed: %s\n", err);
    return 1;
  }
  char plat[64] = {0};
  tfr_pjrt_client_platform(client, plat, sizeof(plat));
  std::fprintf(stderr, "[host_demo] platform=%s devices=%d\n", plat,
               tfr_pjrt_client_device_count(client));

  // one [rows] argument of the header's dtype; refinement of the
  // symbolic row dim happens inside the core
  int dtypes[1] = {dtype};
  int ndims[1] = {1};
  long long dims[1] = {rows};
  tfr_pjrt_exe* exe = tfr_pjrt_compile_dynamic(
      client, blob.data() + payload_off, module_len,
      static_cast<int>(cc_version), platforms.c_str(), plat, 1, dtypes,
      ndims, dims, err, sizeof(err));
  if (!exe) {
    std::fprintf(stderr, "compile failed: %s\n", err);
    tfr_pjrt_client_destroy(client);
    return 1;
  }

  // fabricate 0..rows-1 in the argument's OWN dtype — handing the core a
  // wrong-typed buffer would over/under-read (int64 vs float32 sizes)
  std::vector<double> x64(rows);
  std::vector<float> x32(rows);
  std::vector<long long> i64(rows);
  std::vector<int> i32(rows);
  std::vector<unsigned char> b8(rows);
  for (long i = 0; i < rows; ++i) {
    x64[i] = i; x32[i] = float(i); i64[i] = i; i32[i] = int(i);
    b8[i] = static_cast<unsigned char>(i & 1);
  }
  const void* arg = nullptr;
  switch (dtype) {
    case TFR_F64: arg = x64.data(); break;
    case TFR_F32: arg = x32.data(); break;
    case TFR_I64: arg = i64.data(); break;
    case TFR_I32: arg = i32.data(); break;
    case TFR_PRED: arg = b8.data(); break;
  }
  const void* data[1] = {arg};
  tfr_pjrt_results* res = tfr_pjrt_execute(client, exe, 1, dtypes, ndims,
                                           dims, data, err, sizeof(err));
  if (!res) {
    std::fprintf(stderr, "execute failed: %s\n", err);
    tfr_pjrt_exe_destroy(exe);
    tfr_pjrt_client_destroy(client);
    return 1;
  }
  const int n_out = tfr_pjrt_results_count(res);
  std::fprintf(stderr, "[host_demo] %d output(s)\n", n_out);
  for (int i = 0; i < n_out; ++i) {
    int odt = 0, ondim = 0;
    long long odims[8] = {0};
    if (tfr_pjrt_result_meta(res, i, &odt, &ondim, odims)) {
      std::fprintf(stderr, "result meta failed\n");
      return 1;
    }
    long long elems = 1;
    for (int d = 0; d < ondim; ++d) elems *= odims[d];
    if (odt == TFR_F64) {
      std::vector<double> out(elems);
      if (tfr_pjrt_result_read(res, i, out.data(), elems * 8, err,
                               sizeof(err))) {
        std::fprintf(stderr, "result read failed: %s\n", err);
        return 1;
      }
      std::printf("out[%d] dtype=f64 elems=%lld first=%.6f last=%.6f\n", i,
                  elems, out.empty() ? 0.0 : out.front(),
                  out.empty() ? 0.0 : out.back());
    } else if (odt == TFR_F32) {
      std::vector<float> out(elems);
      if (tfr_pjrt_result_read(res, i, out.data(), elems * 4, err,
                               sizeof(err))) {
        std::fprintf(stderr, "result read failed: %s\n", err);
        return 1;
      }
      std::printf("out[%d] dtype=f32 elems=%lld first=%.6f last=%.6f\n", i,
                  elems, out.empty() ? 0.f : out.front(),
                  out.empty() ? 0.f : out.back());
    } else {
      std::printf("out[%d] dtype_code=%d elems=%lld (not printed)\n", i,
                  odt, elems);
    }
  }
  tfr_pjrt_results_destroy(res);
  tfr_pjrt_exe_destroy(exe);
  tfr_pjrt_client_destroy(client);
  std::printf("HOST_DEMO_OK\n");
  return 0;
}
